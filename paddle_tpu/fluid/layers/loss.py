"""Loss layers (reference: python/paddle/fluid/layers/loss.py)."""
from __future__ import annotations

from ..core import VarDesc
from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "cross_entropy", "square_error_cost", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "rank_loss", "margin_rank_loss",
    "huber_loss", "kldiv_loss", "mse_loss", "bpr_loss", "center_loss",
    "edit_distance", "warpctc", "nce", "hsigmoid",
    "sampled_softmax_with_cross_entropy", "teacher_student_sigmoid_loss",
    "npair_loss",
]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = tuple(list(input.shape[:-1]) + [1])
    helper.append_op(type="cross_entropy",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="square_error_cost",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    softmax.shape = logits.shape
    lshape = list(logits.shape)
    lshape[axis] = 1
    loss.shape = tuple(lshape)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Softmax": [softmax], "Loss": [loss]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index,
                            "numeric_stable_mode": numeric_stable_mode,
                            "axis": axis})
    if return_softmax:
        return loss, softmax
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x], "Label": [label]},
                     outputs={"Out": [out]},
                     attrs={"ignore_index": ignore_index,
                            "normalize": normalize})
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    out.shape = left.shape
    helper.append_op(type="rank_loss",
                     inputs={"Label": [label], "Left": [left],
                             "Right": [right]},
                     outputs={"Out": [out]})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    out.shape = left.shape
    helper.append_op(type="margin_rank_loss",
                     inputs={"Label": [label], "X1": [left], "X2": [right]},
                     outputs={"Out": [out], "Activated": [act]},
                     attrs={"margin": margin})
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="huber_loss",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out], "Residual": [residual]},
                     attrs={"delta": delta})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="kldiv_loss",
                     inputs={"X": [x], "Target": [target]},
                     outputs={"Loss": [out]}, attrs={"reduction": reduction})
    return out


def mse_loss(input, label):
    helper = LayerHelper("mse_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = (1,)
    helper.append_op(type="mse_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = (input.shape[0], 1)
    helper.append_op(type="bpr_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]})
    return out


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """reference: layers/loss.py center_loss — intra-class center pull;
    Centers updated in place by the op (CentersOut aliases Centers)."""
    from ..initializer import Constant
    helper = LayerHelper("center_loss", **locals())
    dtype = helper.input_dtype()
    centers = helper.create_parameter(
        attr=param_attr, shape=[num_classes, input.shape[-1]], dtype=dtype,
        default_initializer=Constant(0.0))
    centers.stop_gradient = True
    rate = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="fill_constant", inputs={},
                     outputs={"Out": [rate]},
                     attrs={"shape": [1], "value": float(alpha),
                            "dtype": rate.dtype})
    loss = helper.create_variable_for_type_inference(dtype)
    diff = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="center_loss",
        inputs={"X": [input], "Label": [label], "Centers": [centers],
                "CenterUpdateRate": [rate]},
        outputs={"Loss": [loss], "SampleCenterDiff": [diff],
                 "CentersOut": [centers]},
        attrs={"cluster_num": num_classes, "alpha": float(alpha),
               "need_update": update_center})
    return loss


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    from ..core import VarDesc
    helper = LayerHelper("edit_distance")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.FP32)
    seq_num = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT64)
    helper.append_op(type="edit_distance",
                     inputs={"Hyps": [input], "Refs": [label]},
                     outputs={"Out": [out], "SequenceNum": [seq_num]},
                     attrs={"normalized": normalized})
    return out, seq_num


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(input.dtype)
    loss.shape = (-1, 1)
    helper.append_op(type="warpctc",
                     inputs={"Logits": [input], "Label": [label]},
                     outputs={"Loss": [loss]},
                     attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """reference: layers/loss.py nce — NCE over sampled negatives."""
    helper = LayerHelper("nce", **locals())
    dtype = helper.input_dtype()
    dim = input.shape[-1]
    w = helper.create_parameter(attr=param_attr,
                                shape=[num_total_classes, dim], dtype=dtype)
    b = (helper.create_parameter(attr=bias_attr,
                                 shape=[num_total_classes, 1], dtype=dtype,
                                 is_bias=True)
         if bias_attr is not False else None)
    cost = helper.create_variable_for_type_inference(dtype)
    cost.shape = (-1, 1)
    slog = helper.create_variable_for_type_inference(dtype)
    slab = helper.create_variable_for_type_inference(label.dtype)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if b is not None:
        inputs["Bias"] = [b]
    helper.append_op(
        type="nce", inputs=inputs,
        outputs={"Cost": [cost], "SampleLogits": [slog],
                 "SampleLabels": [slab]},
        attrs={"num_total_classes": num_total_classes,
               "num_neg_samples": num_neg_samples or 10, "seed": seed,
               "sampler": {"uniform": 0, "log_uniform": 1,
                           "custom_dist": 2}.get(sampler, 0),
               "is_sparse": is_sparse})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """reference: layers/loss.py hsigmoid — complete-binary-tree codes."""
    helper = LayerHelper("hierarchical_sigmoid", **locals())
    dtype = helper.input_dtype()
    dim = input.shape[-1]
    w = helper.create_parameter(attr=param_attr,
                                shape=[num_classes - 1, dim], dtype=dtype)
    b = (helper.create_parameter(attr=bias_attr, shape=[num_classes - 1, 1],
                                 dtype=dtype, is_bias=True)
         if bias_attr is not False else None)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = (-1, 1)
    pre = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [input], "W": [w], "Label": [label]}
    if b is not None:
        inputs["Bias"] = [b]
    helper.append_op(type="hierarchical_sigmoid", inputs=inputs,
                     outputs={"Out": [out], "PreOut": [pre]},
                     attrs={"num_classes": num_classes,
                            "is_sparse": is_sparse})
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples, seed=0,
                                       **kw):
    helper = LayerHelper("sampled_softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(logits.dtype)
    loss.shape = (-1, 1)
    helper.append_op(type="sampled_softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Loss": [loss]},
                     attrs={"num_samples": num_samples, "seed": seed})
    return loss


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = (-1, 1)
    helper.append_op(type="teacher_student_sigmoid_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_max_up_bound": soft_max_up_bound,
                            "soft_max_lower_bound": soft_max_lower_bound})
    return out


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    from .nn import reduce_sum, reduce_mean, matmul, transpose
    from . import ops
    from .loss import softmax_with_cross_entropy
    reg = reduce_mean(reduce_sum(ops.square(anchor), 1)) + reduce_mean(
        reduce_sum(ops.square(positive), 1))
    l2loss = reg * l2_reg * 0.25
    sim = matmul(anchor, positive, transpose_y=True)
    from .nn import softmax as _sm
    import numpy as _np
    ce = softmax_with_cross_entropy(sim, labels, soft_label=True)
    return reduce_mean(ce) + l2loss
