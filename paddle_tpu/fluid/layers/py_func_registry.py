"""Registry backing the py_func op (reference: operators/py_func_op.cc keeps
a global callable vector; same idea host-side)."""
from __future__ import annotations

_CALLABLES = []


def register_callable(fn) -> int:
    _CALLABLES.append(fn)
    return len(_CALLABLES) - 1


def get_callable(idx: int):
    return _CALLABLES[idx]
