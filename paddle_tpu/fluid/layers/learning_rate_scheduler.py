"""LR schedulers as in-graph ops (reference:
python/paddle/fluid/layers/learning_rate_scheduler.py). Each returns a
Variable recomputed each step from the auto-incremented global counter."""
from __future__ import annotations

import math

from ..core import VarDesc
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper
from ..initializer import Constant
from .nn import autoincreased_step_counter, elementwise_div
from .tensor import fill_constant, cast
from . import ops
from . import control_flow
from .control_flow import Switch

__all__ = [
    "exponential_decay", "natural_exp_decay", "inverse_time_decay",
    "polynomial_decay", "piecewise_decay", "noam_decay", "cosine_decay",
    "linear_lr_warmup",
]


def _decay_step_counter(begin=0):
    counter = autoincreased_step_counter(
        counter_name="@LR_DECAY_COUNTER@", begin=begin, step=1)
    return cast(counter, "float32")


def noam_decay(d_model, warmup_steps):
    step = _decay_step_counter(1)
    a = step ** -0.5
    b = step * (warmup_steps ** -1.5)
    from .nn import elementwise_min
    return (d_model ** -0.5) * elementwise_min(a, b)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return float(learning_rate) * (float(decay_rate) ** div)


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return float(learning_rate) * ops.exp(div * float(-decay_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return float(learning_rate) / (div * float(decay_rate) + 1.0)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _decay_step_counter()
    if cycle:
        div_res = ops.ceil(step / float(decay_steps))
        from .nn import equal as _  # noqa
        decay_steps_var = div_res * float(decay_steps)
        # guard step==0 → one cycle
        decayed = (step / decay_steps_var)
        frac = 1.0 - decayed
    else:
        from .nn import elementwise_min
        capped = elementwise_min(
            step, fill_constant([1], "float32", float(decay_steps)))
        frac = 1.0 - capped / float(decay_steps)
    return ((float(learning_rate) - float(end_learning_rate))
            * (frac ** power)) + float(end_learning_rate)


def piecewise_decay(boundaries, values):
    helper = LayerHelper("piecewise_decay")
    step = autoincreased_step_counter(counter_name="@LR_DECAY_COUNTER@",
                                      begin=0, step=1)
    lr = helper.create_or_get_global_variable(
        name=helper.name + ".lr", dtype=VarDesc.VarType.FP32, shape=[1])
    lr.persistable = True
    helper.set_variable_initializer(lr, Constant(float(values[0])))
    with Switch() as switch:
        for i, b in enumerate(boundaries):
            bval = fill_constant([1], VarDesc.VarType.INT64, int(b))
            with switch.case(control_flow.less_than(step, bval)):
                v = fill_constant([1], "float32", float(values[i]))
                from .tensor import assign
                assign(v, lr)
        with switch.default():
            v = fill_constant([1], "float32", float(values[-1]))
            from .tensor import assign
            assign(v, lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _decay_step_counter()
    epoch = ops.floor(step / float(step_each_epoch))
    return float(learning_rate) * 0.5 * (
        ops.cos(epoch * (math.pi / float(epochs))) + 1.0)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    helper = LayerHelper("linear_warmup")
    lr = helper.create_or_get_global_variable(
        name=helper.name + ".warmup_lr", dtype=VarDesc.VarType.FP32,
        shape=[1])
    lr.persistable = True
    helper.set_variable_initializer(lr, Constant(float(start_lr)))
    step = autoincreased_step_counter(counter_name="@LR_DECAY_COUNTER@",
                                      begin=0, step=1)
    with Switch() as switch:
        wval = fill_constant([1], VarDesc.VarType.INT64, int(warmup_steps))
        with switch.case(control_flow.less_than(step, wval)):
            fstep = cast(step, "float32")
            warm = float(start_lr) + (float(end_lr) - float(start_lr)) \
                * fstep / float(warmup_steps)
            from .tensor import assign
            assign(warm, lr)
        with switch.default():
            from .tensor import assign
            if isinstance(learning_rate, Variable):
                assign(learning_rate, lr)
            else:
                assign(fill_constant([1], "float32", float(learning_rate)), lr)
    return lr
