"""Sequence layers over LoD tensors (reference:
python/paddle/fluid/layers/sequence_lod.py).

TPU strategy: the packed buffer is the device array; LoD offsets are
host-static trace metadata (see ops/sequence_ops.py) so every sequence op
lowers to constant-index segment/gather XLA ops — no dynamic shapes."""
from __future__ import annotations

from ..core import VarDesc
from ..layer_helper import LayerHelper

__all__ = [
    "sequence_conv", "sequence_softmax", "sequence_pool", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate", "sequence_mask", "sequence_reverse",
]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ..core import convert_np_dtype_to_dtype_
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(
        convert_np_dtype_to_dtype_(dtype))
    inputs = {"X": [x]}
    attrs = {"out_dtype": convert_np_dtype_to_dtype_(dtype)}
    if maxlen is not None and not isinstance(maxlen, (int,)):
        inputs["MaxLenTensor"] = [maxlen]
        attrs["maxlen"] = -1
    else:
        attrs["maxlen"] = maxlen if maxlen is not None else -1
    helper.append_op(type="sequence_mask", inputs=inputs,
                     outputs={"Y": [out]}, attrs=attrs)
    return out


def _simple(op_type, x, out_slot="Out", extra_inputs=None, **attrs):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(x.dtype)
    # sequence ops keep the feature dims; the row count is LoD-dynamic.
    # Without this, downstream builders (concat width -> fc weight
    # shapes) silently see () and create wrong parameters.
    if x.shape:
        out.shape = (-1,) + tuple(x.shape[1:])
    inputs = {"X": [x]}
    if extra_inputs:
        inputs.update(extra_inputs)
    helper.append_op(type=op_type, inputs=inputs,
                     outputs={out_slot: [out]}, attrs=attrs)
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """reference: layers/sequence_lod.py sequence_conv."""
    helper = LayerHelper("sequence_conv", **locals())
    dtype = helper.input_dtype()
    in_dim = input.shape[-1]
    filter_shape = [filter_size * in_dim, num_filters]
    filter_param = helper.create_parameter(attr=param_attr,
                                           shape=filter_shape, dtype=dtype)
    if padding_start is None:
        padding_start = -int(filter_size // 2)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    pre_bias.shape = tuple(input.shape[:-1]) + (num_filters,)
    helper.append_op(
        type="sequence_conv",
        inputs={"X": [input], "Filter": [filter_param]},
        outputs={"Out": [pre_bias]},
        attrs={"contextStride": filter_stride, "contextStart": padding_start,
               "contextLength": filter_size})
    pre_act = helper.append_bias_op(pre_bias)
    return helper.append_activation(pre_act)


def sequence_softmax(input, use_cudnn=False, name=None):
    return _simple("sequence_softmax", input)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    helper = LayerHelper("sequence_pool")
    pool_out = helper.create_variable_for_type_inference(input.dtype)
    pool_out.shape = tuple(input.shape)
    max_index = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT32)
    helper.append_op(type="sequence_pool",
                     inputs={"X": [input]},
                     outputs={"Out": [pool_out], "MaxIndex": [max_index]},
                     attrs={"pooltype": pool_type.upper(),
                            "is_test": is_test, "pad_value": pad_value})
    return pool_out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="sequence_concat", inputs={"X": list(input)},
                     outputs={"Out": [out]})
    return out


def sequence_slice(input, offset, length, name=None):
    return _simple("sequence_slice", input,
                   extra_inputs={"Offset": [offset], "Length": [length]})


def sequence_expand(x, y, ref_level=-1, name=None):
    return _simple("sequence_expand", x, extra_inputs={"Y": [y]},
                   ref_level=ref_level)


def sequence_expand_as(x, y, name=None):
    return _simple("sequence_expand_as", x, extra_inputs={"Y": [y]})


def sequence_pad(x, pad_value, maxlen=None, name=None):
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT64)
    helper.append_op(type="sequence_pad",
                     inputs={"X": [x], "PadValue": [pad_value]},
                     outputs={"Out": [out], "Length": [length]},
                     attrs={"padded_length": maxlen if maxlen else -1})
    return out, length


def sequence_unpad(x, length, name=None):
    return _simple("sequence_unpad", x,
                   extra_inputs={"Length": [length]})


def sequence_reshape(input, new_dim):
    return _simple("sequence_reshape", input, new_dim=new_dim)


def sequence_scatter(input, index, updates, name=None):
    return _simple("sequence_scatter", input,
                   extra_inputs={"Ids": [index], "Updates": [updates]})


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    return _simple("sequence_enumerate", input, win_size=win_size,
                   pad_value=pad_value)


def sequence_reverse(x, name=None):
    return _simple("sequence_reverse", x, out_slot="Y")
