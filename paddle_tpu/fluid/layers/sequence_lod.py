"""Sequence layers over LoD tensors (reference:
python/paddle/fluid/layers/sequence_lod.py). TPU strategy: ragged sequences
run as padded/packed dense ops (sequence_pad/unpad/mask are the bridge);
true LoD-dependent ops execute in interpreter mode where LoD metadata is
host-side. Round-1 provides the padded-path ops; LoD-interpreted ops land
with the sequence batch."""
from __future__ import annotations

from ..core import VarDesc
from ..layer_helper import LayerHelper

__all__ = [
    "sequence_conv", "sequence_softmax", "sequence_pool", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate", "sequence_mask", "sequence_reverse",
]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ..core import convert_np_dtype_to_dtype_
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(
        convert_np_dtype_to_dtype_(dtype))
    inputs = {"X": [x]}
    attrs = {"out_dtype": convert_np_dtype_to_dtype_(dtype)}
    if maxlen is not None and not isinstance(maxlen, (int,)):
        inputs["MaxLenTensor"] = [maxlen]
        attrs["maxlen"] = -1
    else:
        attrs["maxlen"] = maxlen if maxlen is not None else -1
    helper.append_op(type="sequence_mask", inputs=inputs,
                     outputs={"Y": [out]}, attrs=attrs)
    return out


def _nyi(name):
    def fn(*a, **k):
        raise NotImplementedError(
            f"{name}: LoD sequence op pending (interpreter batch)")
    fn.__name__ = name
    return fn


sequence_conv = _nyi("sequence_conv")
sequence_softmax = _nyi("sequence_softmax")
sequence_pool = _nyi("sequence_pool")
sequence_concat = _nyi("sequence_concat")
sequence_first_step = _nyi("sequence_first_step")
sequence_last_step = _nyi("sequence_last_step")
sequence_slice = _nyi("sequence_slice")
sequence_expand = _nyi("sequence_expand")
sequence_expand_as = _nyi("sequence_expand_as")
sequence_pad = _nyi("sequence_pad")
sequence_unpad = _nyi("sequence_unpad")
sequence_reshape = _nyi("sequence_reshape")
sequence_scatter = _nyi("sequence_scatter")
sequence_enumerate = _nyi("sequence_enumerate")
sequence_reverse = _nyi("sequence_reverse")
