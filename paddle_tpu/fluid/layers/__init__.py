"""fluid.layers — op-builder functions (reference: python/paddle/fluid/layers/).

Each function appends ops to the current program block and returns output
Variables; in dygraph mode append_op routes through the tracer and executes
immediately (reference framework.py:2758,2781)."""
from . import tensor as _tensor_mod
from .tensor import *          # noqa: F401,F403
from . import nn as _nn_mod
from .nn import *              # noqa: F401,F403
from . import ops as _ops_mod
from .ops import *             # noqa: F401,F403
from . import loss as _loss_mod
from .loss import *            # noqa: F401,F403
from . import control_flow as _cf_mod
from .control_flow import *    # noqa: F401,F403
from . import learning_rate_scheduler as _lrs_mod
from .learning_rate_scheduler import *  # noqa: F401,F403
from . import metric_op as _metric_mod
from .metric_op import *       # noqa: F401,F403
from . import io as _io_mod
from .io import *              # noqa: F401,F403
from . import sequence_lod as _seq_mod
from .sequence_lod import *    # noqa: F401,F403
from . import collective as _coll_mod
from . import collective  # noqa: F401
# the reference exports these underscore helpers at layers scope
# (layers/collective.py __all__ lists them, so * picks them up there)
from .collective import (_allreduce, _broadcast, _c_allreduce,  # noqa: F401
                         _c_broadcast, _c_allgather,  # noqa: F401
                         _c_reducescatter, _c_sync_calc_stream,  # noqa: F401
                         _c_sync_comm_stream)  # noqa: F401
from . import detection as _det_mod
from .detection import *       # noqa: F401,F403
from . import rnn as _rnn_mod
from .rnn import *             # noqa: F401,F403
from . import distributions  # noqa: F401
from .distributions import (Uniform, Normal, Categorical,  # noqa: F401
                            MultivariateNormalDiag)  # noqa: F401

from .tensor import math_op  # noqa: F401
