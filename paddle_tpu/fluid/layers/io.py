"""Data-input layers (reference: python/paddle/fluid/layers/io.py — data,
py_reader, double_buffer). On TPU the device feed pipeline is the host→HBM
transfer inside jit; py_reader maps to the DataLoader path (fluid/reader.py)."""
from __future__ import annotations

from ..core import VarDesc, convert_np_dtype_to_dtype_
from ..framework import default_main_program, default_startup_program
from ..layer_helper import LayerHelper

__all__ = ["data", "read_file", "double_buffer"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=VarDesc.VarType.LOD_TENSOR, stop_gradient=True):
    helper = LayerHelper("data")
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.main_program.global_block().create_var(
        name=name, shape=shape, dtype=convert_np_dtype_to_dtype_(dtype),
        lod_level=lod_level, type=type, stop_gradient=stop_gradient,
        is_data=True, need_check_feed=True)


def read_file(reader):
    raise NotImplementedError("read_file: use DataLoader feeds")


def double_buffer(reader, place=None, name=None):
    return reader
