"""Data-input layers (reference: python/paddle/fluid/layers/io.py — data,
py_reader, double_buffer). On TPU the device feed pipeline is the host→HBM
transfer inside jit; py_reader maps to the DataLoader path (fluid/reader.py)."""
from __future__ import annotations

from ..core import VarDesc, convert_np_dtype_to_dtype_
from ..framework import default_main_program, default_startup_program
from ..layer_helper import LayerHelper

__all__ = ["data", "read_file", "double_buffer", "py_reader",
           "create_py_reader_by_data", "load"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=VarDesc.VarType.LOD_TENSOR, stop_gradient=True):
    helper = LayerHelper("data")
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.main_program.global_block().create_var(
        name=name, shape=shape, dtype=convert_np_dtype_to_dtype_(dtype),
        lod_level=lod_level, type=type, stop_gradient=stop_gradient,
        is_data=True, need_check_feed=True)


def read_file(reader):
    """Consume one batch from a py_reader handle (reference layers/io.py
    read_file over the read op). The PyReader loader yields feed dicts;
    in-graph consumption maps to the declared data vars."""
    from ..reader import PyReader
    if isinstance(reader, PyReader):
        return list(reader._feed_list)
    raise NotImplementedError("read_file: pass the py_reader handle, or "
                              "feed batches through DataLoader")


def double_buffer(reader, place=None, name=None):
    return reader


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Legacy in-graph reader (reference layers/io.py py_reader →
    create_py_reader + LoDTensorBlockingQueue). Returns a PyReader whose
    decorate_* methods accept the python-side generators; the executor
    consumes its batches as feeds — the TPU build's double buffering is
    the loader's background prefetch thread."""
    from ..reader import PyReader
    names = [(name or "py_reader") + f"_{i}" for i in range(len(shapes))]
    lod_levels = lod_levels or [0] * len(shapes)
    feed_vars = [data(n, shape=list(s), append_batch_size=False, dtype=d,
                      lod_level=l)
                 for n, s, d, l in zip(names, shapes, dtypes, lod_levels)]
    return PyReader(feed_list=feed_vars, capacity=capacity,
                    use_double_buffer=use_double_buffer, iterable=True)


def load(out, file_path, load_as_fp16=None):
    """Append a load op restoring ``out`` from a saved tensor file
    (reference layers/io.py load → load_op.cc)."""
    helper = LayerHelper("load")
    attrs = {"file_path": file_path}
    if load_as_fp16 is not None:
        attrs["load_as_fp16"] = bool(load_as_fp16)
    helper.append_op(type="load", inputs={}, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """reference layers/io.py create_py_reader_by_data — py_reader over
    existing data vars."""
    from ..reader import PyReader
    return PyReader(feed_list=list(feed_list), capacity=capacity,
                    use_double_buffer=use_double_buffer, iterable=True)
