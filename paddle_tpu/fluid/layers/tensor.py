"""Tensor creation/manipulation layers (reference:
python/paddle/fluid/layers/tensor.py)."""
from __future__ import annotations

import numpy as np

from .. import core, unique_name
from ..core import VarDesc, convert_np_dtype_to_dtype_
from ..framework import Variable, default_main_program, in_dygraph_mode
from ..layer_helper import LayerHelper
from ..initializer import Constant

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "cast",
    "tensor_array_to_tensor", "concat", "sums", "assign",
    "fill_constant_batch_size_like", "fill_constant", "argmin", "argmax",
    "argsort", "ones", "zeros", "reverse", "has_inf", "has_nan", "isfinite",
    "range", "linspace", "zeros_like", "ones_like", "diag", "eye",
]


def _dtype(d):
    return d if isinstance(d, int) else convert_np_dtype_to_dtype_(d)


def math_op(op_type, x, y):
    """Helper for Variable operator overloading."""
    helper = LayerHelper(op_type)
    if not isinstance(y, Variable):
        yv = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(type="fill_constant", outputs={"Out": [yv]},
                         attrs={"shape": [1], "dtype": x.dtype,
                                "value": float(y)})
        yv.shape = (1,)
        y = yv
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape if len(x.shape) >= len(y.shape) else y.shape
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=_dtype(dtype),
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..param_attr import ParamAttr
    helper = LayerHelper("create_parameter", **locals())
    if attr is None:
        attr = ParamAttr(name=name)
    return helper.create_parameter(attr, shape, _dtype(dtype), is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(dtype=_dtype(dtype), shape=shape,
                                        persistable=persistable,
                                        stop_gradient=True)
    helper.set_variable_initializer(var, Constant(value=float(value)))
    return var


def cast(x, dtype):
    dtype = _dtype(dtype)
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = x.shape
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": x.dtype, "out_dtype": dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=helper.input_dtype() if False else input[0].dtype)
    inputs = {"X": list(input)}
    attrs = {}
    if isinstance(axis, Variable):
        inputs["AxisTensor"] = [axis]
        attrs["axis"] = 0
    else:
        attrs["axis"] = axis
    shapes = [list(v.shape) for v in input]
    if all(s for s in shapes):
        shp = list(shapes[0])
        ax = axis if not isinstance(axis, Variable) else 0
        if shp:
            shp[ax] = sum(s[ax] for s in shapes) if all(
                s[ax] >= 0 for s in shapes) else -1
        out.shape = tuple(shp)
    helper.append_op(type="concat", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
        out.shape = input[0].shape
    helper.append_op(type="sum", inputs={"X": list(input)},
                     outputs={"Out": [out]}, attrs={})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=input.dtype)
            output.shape = input.shape
        helper.append_op(type="assign", inputs={"X": [input]},
                         outputs={"Out": [output]})
    elif isinstance(input, (np.ndarray, list, tuple, float, int)):
        arr = np.asarray(input)
        dtype = convert_np_dtype_to_dtype_(arr.dtype)
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=dtype)
            output.shape = arr.shape
        if arr.dtype in (np.float32, np.float64):
            values = {"fp32_values": [float(v) for v in arr.flatten()]}
        elif arr.dtype == np.bool_:
            values = {"bool_values": [bool(v) for v in arr.flatten()]}
        elif arr.dtype == np.int64:
            values = {"int64_values": [int(v) for v in arr.flatten()]}
        else:
            values = {"int32_values": [int(v) for v in arr.flatten()]}
        helper.append_op(type="assign_value", outputs={"Out": [output]},
                         attrs={"shape": list(arr.shape), "dtype": dtype,
                                **values})
    else:
        raise TypeError(f"cannot assign {type(input)}")
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    attrs = {"value": float(value), "dtype": _dtype(dtype)}
    inputs = {}
    if isinstance(shape, Variable):
        inputs["ShapeTensor"] = [shape]
        attrs["shape"] = []
        known = None
    elif isinstance(shape, (list, tuple)) and any(
            isinstance(s, Variable) for s in shape):
        inputs["ShapeTensorList"] = [s for s in shape if isinstance(s, Variable)]
        attrs["shape"] = [s if not isinstance(s, Variable) else -1 for s in shape]
        known = None
    else:
        attrs["shape"] = [int(s) for s in shape]
        known = tuple(int(s) for s in shape)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=attrs["dtype"])
    out.stop_gradient = True
    if known is not None:
        out.shape = known
    helper.append_op(type="fill_constant", inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype=_dtype(dtype))
    out.shape = tuple(shape)
    out.stop_gradient = True
    helper.append_op(type="fill_constant_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": _dtype(dtype),
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=0.0)


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(type="fill_any_like", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"value": 1.0, "dtype": -1})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(type="fill_any_like", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"value": 0.0, "dtype": -1})
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    helper.append_op(type="arg_min", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    out.stop_gradient = True
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    shp = list(x.shape)
    if shp:
        shp.pop(axis if axis >= 0 else len(shp) + axis)
    out.shape = tuple(shp)
    helper.append_op(type="arg_max", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    out.stop_gradient = True
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    out.shape = ids.shape = input.shape
    helper.append_op(type="argsort", inputs={"X": [input]},
                     outputs={"Out": [out], "Indices": [ids]},
                     attrs={"axis": axis, "descending": descending})
    return out, ids


def reverse(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="reverse", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def has_inf(x):
    """True iff ``x`` contains an Inf (reference: isinf over AnyVisitor
    — a NaN-only tensor reports False; NOT the same as ``not
    isfinite``, which the old port conflated both helpers into)."""
    helper = LayerHelper("isinf")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.BOOL)
    out.shape = (1,)
    helper.append_op(type="isinf", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def has_nan(x):
    """True iff ``x`` contains a NaN (an Inf-only tensor reports
    False)."""
    helper = LayerHelper("isnan")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.BOOL)
    out.shape = (1,)
    helper.append_op(type="isnan", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def isfinite(x):
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.BOOL)
    out.shape = (1,)
    helper.append_op(type="isfinite", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def range(start, end, step, dtype):
    helper = LayerHelper("range")
    dtype = _dtype(dtype)

    def _ensure(v):
        if isinstance(v, Variable):
            return v
        return fill_constant([1], dtype, v)
    start, end, step = _ensure(start), _ensure(end), _ensure(step)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="range",
                     inputs={"Start": [start], "End": [end], "Step": [step]},
                     outputs={"Out": [out]})
    out.stop_gradient = True
    return out


def linspace(start, stop, num, dtype):
    helper = LayerHelper("linspace")
    dtype = _dtype(dtype)

    def _ensure(v, dt):
        if isinstance(v, Variable):
            return v
        return fill_constant([1], dt, v)
    start = _ensure(start, dtype)
    stop = _ensure(stop, dtype)
    num = _ensure(num, VarDesc.VarType.INT32)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="linspace",
                     inputs={"Start": [start], "Stop": [stop], "Num": [num]},
                     outputs={"Out": [out]})
    return out


def diag(diagonal):
    helper = LayerHelper("diag")
    out = helper.create_variable_for_type_inference(diagonal.dtype)
    helper.append_op(type="diag", inputs={"Diagonal": [diagonal]},
                     outputs={"Out": [out]})
    out.stop_gradient = True
    return out


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    helper = LayerHelper("eye")
    dtype = _dtype(dtype)
    num_columns = num_columns if num_columns is not None else num_rows
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = (num_rows, num_columns)
    helper.append_op(type="eye", outputs={"Out": [out]},
                     attrs={"num_rows": num_rows, "num_columns": num_columns,
                            "dtype": dtype})
    out.stop_gradient = True
    return out


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference(VarDesc.VarType.INT32)
    helper.append_op(type="tensor_array_to_tensor", inputs={"X": [input]},
                     outputs={"Out": [out], "OutIndex": [idx]},
                     attrs={"axis": axis, "use_stack": use_stack})
    return out, idx
