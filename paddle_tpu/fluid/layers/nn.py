"""Core NN layers (reference: python/paddle/fluid/layers/nn.py, 14.4K LoC).
Op-builder functions with inline shape inference; -1 marks unknown dims."""
from __future__ import annotations

import numpy as np

from .. import core
from ..core import VarDesc, convert_np_dtype_to_dtype_
from ..framework import Variable
from ..layer_helper import LayerHelper
from ..initializer import Constant, Normal, Xavier
from ..param_attr import ParamAttr

__all__ = [
    "fc", "embedding", "conv2d", "conv3d", "conv2d_transpose", "pool2d",
    "pool3d", "adaptive_pool2d", "batch_norm", "instance_norm", "layer_norm",
    "group_norm", "data_norm", "dropout", "softmax", "reshape", "squeeze",
    "unsqueeze", "transpose", "split", "concat_", "reduce_sum", "reduce_mean",
    "reduce_max", "reduce_min", "reduce_prod", "reduce_all", "reduce_any",
    "matmul", "topk", "stack", "unstack", "expand", "expand_as", "slice",
    "strided_slice", "gather", "gather_nd", "scatter", "scatter_nd_add",
    "scatter_nd", "one_hot", "l2_normalize", "clip", "clip_by_norm", "mean",
    "mul", "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min", "elementwise_pow",
    "elementwise_mod", "elementwise_floordiv", "uniform_random",
    "gaussian_random", "flatten", "pad", "pad2d", "label_smooth", "where",
    "sign", "shard_index", "relu", "logical_and", "logical_or", "logical_xor",
    "logical_not", "shape", "rank", "size", "lod_reset", "lod_append",
    "image_resize", "resize_bilinear", "resize_nearest", "grid_sampler",
    "unfold", "crop", "crop_tensor", "sum", "cast_", "maxout",
    "space_to_depth", "affine_channel", "similarity_focus", "hash",
    "log_loss", "add_position_encoding", "bilinear_tensor_product",
    "merge_selected_rows", "get_tensor_from_selected_rows", "py_func",
    "pixel_shuffle", "fsp_matrix", "continuous_value_model", "unique",
    "unique_with_counts", "interpolate", "smooth_l1", "multiplex",
    "prelu", "brelu", "leaky_relu", "soft_relu", "swish", "hard_swish",
    "elu", "relu6", "pow", "stanh", "hard_sigmoid", "im2sequence",
    "row_conv", "autoincreased_step_counter", "unbind", "roll",
    "index_select", "index_sample", "temporal_shift", "spectral_norm",
    "random_crop", "mean_iou", "dice_loss",
    "linear_chain_crf", "crf_decoding", "cos_sim", "lrn",
    "pad_constant_like", "roi_pool", "roi_align", "scale",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
    "sampling_id", "shuffle_channel", "adaptive_pool3d", "inplace_abn",
    "ctc_greedy_decoder",
    "conv3d_transpose", "resize_trilinear", "image_resize_short",
    "affine_grid", "psroi_pool", "prroi_pool", "deformable_conv",
    "deformable_roi_pooling", "chunk_eval", "filter_by_instag",
]


def _prod(xs):
    r = 1
    for x in xs:
        r *= x
    return r


# --------------------------------------------------------------------------
def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """reference: layers/nn.py fc — mul(+sum) + bias + act."""
    helper = LayerHelper("fc", **locals())
    dtype = helper.input_dtype()
    inputs = helper.multiple_input()
    mul_results = []
    for inp, pa in zip(inputs, helper.multiple_param_attr(len(inputs))):
        shape = inp.shape
        in_features = _prod(shape[num_flatten_dims:])
        w = helper.create_parameter(attr=pa, shape=[in_features, size],
                                    dtype=dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        tmp.shape = tuple(shape[:num_flatten_dims]) + (size,)
        helper.append_op(type="mul", inputs={"X": [inp], "Y": [w]},
                         outputs={"Out": [tmp]},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        pre_bias.shape = mul_results[0].shape
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference: layers/nn.py embedding → lookup_table op."""
    helper = LayerHelper("embedding", **locals())
    dtype = convert_np_dtype_to_dtype_(dtype)
    w = helper.create_parameter(attr=helper.param_attr, shape=size,
                                dtype=dtype, is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    pad = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    ishape = list(input.shape)
    # v1 op contract wants Ids [..., 1]; ids without the trailing 1 go
    # through lookup_table_v2 (reference: lookup_table_v2_op.cc)
    if ishape and ishape[-1] == 1:
        out.shape = tuple(ishape[:-1]) + (size[1],)
        op_type = "lookup_table"
    else:
        out.shape = tuple(ishape) + (size[1],)
        op_type = "lookup_table_v2"
    helper.append_op(type=op_type,
                     inputs={"W": [w], "Ids": [input]},
                     outputs={"Out": [out]},
                     attrs={"is_sparse": is_sparse,
                            "is_distributed": is_distributed,
                            "remote_prefetch": False,
                            "padding_idx": pad})
    return out


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


def _conv_out_size(i, k, p0, p1, s, d=1):
    if i < 0:
        return -1
    return (i + p0 + p1 - (d * (k - 1) + 1)) // s + 1


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d", **locals())
    dtype = helper.input_dtype()
    groups = groups or 1
    ksize = _pair(filter_size)
    stride = _pair(stride)
    dilation = _pair(dilation)
    pad_algo = "EXPLICIT"
    if isinstance(padding, str):
        pad_algo = padding.upper()
        padding = [0, 0]
    padding = _pair(padding)
    ch_axis = 1 if data_format == "NCHW" else 3
    num_channels = input.shape[ch_axis]
    w_shape = [num_filters, num_channels // groups] + ksize
    default_init = Normal(0.0, (2.0 / (num_channels // groups * _prod(ksize))) ** 0.5)
    w = helper.create_parameter(attr=helper.param_attr, shape=w_shape,
                                dtype=dtype, default_initializer=default_init)
    out = helper.create_variable_for_type_inference(dtype)
    if data_format == "NCHW":
        h = _conv_out_size(input.shape[2], ksize[0], padding[0], padding[0],
                           stride[0], dilation[0])
        wd = _conv_out_size(input.shape[3], ksize[1], padding[1], padding[1],
                            stride[1], dilation[1])
        out.shape = (input.shape[0], num_filters, h, wd)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups, "use_cudnn": use_cudnn,
               "padding_algorithm": pad_algo, "data_format": data_format})
    pre_act = helper.append_bias_op(out, dim_start=ch_axis,
                                    dim_end=ch_axis + 1)
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    helper = LayerHelper("conv3d", **locals())
    dtype = helper.input_dtype()
    groups = groups or 1
    ksize = _pair(filter_size, 3)
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    padding = _pair(padding, 3)
    num_channels = input.shape[1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_filters, num_channels // groups] + ksize,
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups, "use_cudnn": use_cudnn,
               "padding_algorithm": "EXPLICIT", "data_format": data_format})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d_transpose", **locals())
    dtype = helper.input_dtype()
    groups = groups or 1
    stride = _pair(stride)
    dilation = _pair(dilation)
    padding = _pair(padding)
    in_c = input.shape[1]
    if filter_size is None:
        assert output_size is not None
        output_size = _pair(output_size)
        filter_size = [
            (output_size[i] - (input.shape[2 + i] - 1) * stride[i]
             + 2 * padding[i] - 1) // dilation[i] + 1 for i in (0, 1)]
    else:
        filter_size = _pair(filter_size)
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[in_c, num_filters // groups] + filter_size, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups, "use_cudnn": use_cudnn,
               "output_size": list(_pair(output_size)) if output_size else [],
               "padding_algorithm": "EXPLICIT", "data_format": data_format})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCHW"):
    helper = LayerHelper("pool2d", **locals())
    ksize = _pair(pool_size)
    stride = _pair(pool_stride)
    padding = _pair(pool_padding)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    if global_pooling:
        out.shape = (input.shape[0], input.shape[1], 1, 1)
    elif data_format == "NCHW" and len(input.shape) == 4:
        h = _conv_out_size(input.shape[2], ksize[0], padding[0], padding[0], stride[0])
        w = _conv_out_size(input.shape[3], ksize[1], padding[1], padding[1], stride[1])
        out.shape = (input.shape[0], input.shape[1], h, w)
    helper.append_op(
        type="pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": ksize,
               "global_pooling": global_pooling, "strides": stride,
               "paddings": padding, "use_cudnn": use_cudnn,
               "ceil_mode": ceil_mode, "exclusive": exclusive,
               "data_format": data_format, "padding_algorithm": "EXPLICIT"})
    return out


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCDHW"):
    helper = LayerHelper("pool3d", **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(
        type="pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": _pair(pool_size, 3),
               "global_pooling": global_pooling,
               "strides": _pair(pool_stride, 3),
               "paddings": _pair(pool_padding, 3), "use_cudnn": use_cudnn,
               "ceil_mode": ceil_mode, "exclusive": exclusive,
               "data_format": data_format, "padding_algorithm": "EXPLICIT"})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    helper = LayerHelper("adaptive_pool2d", **locals())
    ksize = _pair(pool_size)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    out.shape = (input.shape[0], input.shape[1], ksize[0], ksize[1])
    helper.append_op(
        type="pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": ksize, "adaptive": True,
               "strides": [1, 1], "paddings": [0, 0],
               "global_pooling": False, "data_format": "NCHW",
               "padding_algorithm": "EXPLICIT"})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    helper = LayerHelper("batch_norm", **locals())
    dtype = helper.input_dtype()
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(attr=helper.param_attr, shape=[c],
                                    dtype=dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(attr=helper.bias_attr, shape=[c],
                                   dtype=dtype, is_bias=True)
    mean = helper.create_parameter(
        attr=ParamAttr(name=moving_mean_name, initializer=Constant(0.0),
                       trainable=False), shape=[c], dtype=dtype)
    mean.stop_gradient = True
    variance = helper.create_parameter(
        attr=ParamAttr(name=moving_variance_name, initializer=Constant(1.0),
                       trainable=False), shape=[c], dtype=dtype)
    variance.stop_gradient = True
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = (input if in_place
           else helper.create_variable_for_type_inference(dtype))
    out.shape = input.shape
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon,
               "is_test": is_test, "data_layout": data_layout,
               "use_global_stats": use_global_stats})
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", **locals())
    dtype = helper.input_dtype()
    c = input.shape[1]
    scale = helper.create_parameter(attr=helper.param_attr, shape=[c],
                                    dtype=dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(attr=helper.bias_attr, shape=[c],
                                   dtype=dtype, is_bias=True)
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input.shape
    helper.append_op(
        type="instance_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias]},
        outputs={"Y": [out], "SavedMean": [saved_mean],
                 "SavedVariance": [saved_var]},
        attrs={"epsilon": epsilon})
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", **locals())
    dtype = helper.input_dtype()
    norm_shape = [_prod(input.shape[begin_norm_axis:])]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(attr=helper.param_attr, shape=norm_shape,
                                    dtype=dtype,
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(attr=helper.bias_attr, shape=norm_shape,
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input.shape
    helper.append_op(type="layer_norm", inputs=inputs,
                     outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", **locals())
    dtype = helper.input_dtype()
    c = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        s = helper.create_parameter(attr=helper.param_attr, shape=[c],
                                    dtype=dtype,
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = [s]
    if bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr, shape=[c],
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input.shape
    helper.append_op(type="group_norm", inputs=inputs,
                     outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
                     attrs={"epsilon": epsilon, "groups": groups,
                            "data_layout": data_layout})
    return helper.append_activation(out)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True):
    helper = LayerHelper("data_norm", **locals())
    dtype = helper.input_dtype()
    c = input.shape[-1]
    batch_size = helper.create_parameter(
        attr=ParamAttr(initializer=Constant(1e4)), shape=[c], dtype=dtype)
    batch_sum = helper.create_parameter(
        attr=ParamAttr(initializer=Constant(0.0)), shape=[c], dtype=dtype)
    batch_square_sum = helper.create_parameter(
        attr=ParamAttr(initializer=Constant(1e4)), shape=[c], dtype=dtype)
    means = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    scales = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input.shape
    helper.append_op(
        type="data_norm",
        inputs={"X": [input], "BatchSize": [batch_size],
                "BatchSum": [batch_sum], "BatchSquareSum": [batch_square_sum]},
        outputs={"Y": [out], "Means": [means], "Scales": [scales]},
        attrs={"epsilon": epsilon})
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    mask = helper.create_variable_for_type_inference(
        VarDesc.VarType.UINT8, stop_gradient=True)
    helper.append_op(
        type="dropout", inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test,
               "fix_seed": seed is not None, "seed": seed or 0,
               "dropout_implementation": dropout_implementation})
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="softmax", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    inputs = {"X": [x]}
    attrs = {}
    if isinstance(shape, Variable):
        inputs["Shape"] = [shape]
        attrs["shape"] = []
    elif any(isinstance(s, Variable) for s in shape):
        inputs["ShapeTensor"] = [s for s in shape if isinstance(s, Variable)]
        attrs["shape"] = [s if not isinstance(s, Variable) else -1 for s in shape]
    else:
        attrs["shape"] = [int(s) for s in shape]
        # static shape inference with 0/-1 rules
        tgt = list(attrs["shape"])
        for i, t in enumerate(tgt):
            if t == 0:
                tgt[i] = x.shape[i]
        if -1 in tgt and all(s >= 0 for s in x.shape):
            known = _prod([t for t in tgt if t != -1])
            tgt[tgt.index(-1)] = _prod(x.shape) // max(known, 1)
        out.shape = tuple(tgt)
    helper.append_op(type="reshape2", inputs=inputs,
                     outputs={"Out": [out], "XShape": [xshape]}, attrs=attrs)
    return helper.append_activation(out)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    shp = [s for i, s in enumerate(input.shape)
           if not (i in [a % max(len(input.shape), 1) for a in axes] and s == 1)]
    out.shape = tuple(shp)
    helper.append_op(type="squeeze2", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": axes})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    shp = list(input.shape)
    for a in sorted(axes):
        shp.insert(a if a >= 0 else len(shp) + a + 1, 1)
    out.shape = tuple(shp)
    helper.append_op(type="unsqueeze2", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": axes})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    if x.shape:
        out.shape = tuple(x.shape[p] for p in perm)
    helper.append_op(type="transpose2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", **locals())
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        sections = []
        sizes = [input.shape[dim] // n] * n if input.shape[dim] > 0 else [-1] * n
    else:
        sections = list(num_or_sections)
        n = len(sections)
        sizes = sections
    outs = []
    for i in range(n):
        o = helper.create_variable_for_type_inference(input.dtype)
        shp = list(input.shape)
        shp[dim] = sizes[i] if not isinstance(sizes[i], Variable) else -1
        o.shape = tuple(shp)
        outs.append(o)
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs},
                     attrs={"axis": dim, "num": 0 if sections else n,
                            "sections": [s if not isinstance(s, Variable)
                                         else -1 for s in sections]})
    return outs


def concat_(input, axis=0, name=None):
    from .tensor import concat
    return concat(input, axis, name)


def _reduce(op_type, input, dim, keep_dim, name):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is None:
        dims = []
        reduce_all = True
        out.shape = (1,)
    else:
        dims = [dim] if isinstance(dim, int) else list(dim)
        reduce_all = len(dims) == len(input.shape)
        nd = [d % len(input.shape) for d in dims]
        if keep_dim:
            out.shape = tuple(1 if i in nd else s
                              for i, s in enumerate(input.shape))
        else:
            out.shape = tuple(s for i, s in enumerate(input.shape)
                              if i not in nd) or (1,)
    helper.append_op(type=op_type, inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"dim": dims or [0], "keep_dim": keep_dim,
                            "reduce_all": reduce_all})
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_all", input, dim, keep_dim, name)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_any", input, dim, keep_dim, name)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    xs, ys = list(x.shape), list(y.shape)
    if len(xs) >= 2 and len(ys) >= 2:
        if transpose_x:
            xs[-1], xs[-2] = xs[-2], xs[-1]
        if transpose_y:
            ys[-1], ys[-2] = ys[-2], ys[-1]
        batch = xs[:-2] if len(xs) >= len(ys) else ys[:-2]
        out.shape = tuple(batch + [xs[-2], ys[-1]])
    helper.append_op(type="matmul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": float(alpha)})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", **locals())
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    inputs = {"X": [input]}
    attrs = {"k": k if not isinstance(k, Variable) else 1}
    if isinstance(k, Variable):
        inputs["K"] = [k]
    else:
        values.shape = tuple(list(input.shape[:-1]) + [k])
        indices.shape = values.shape
    helper.append_op(type="top_k", inputs=inputs,
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs=attrs)
    indices.stop_gradient = True
    return values, indices


def stack(x, axis=0):
    helper = LayerHelper("stack")
    x = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    shp = list(x[0].shape)
    shp.insert(axis if axis >= 0 else len(shp) + axis + 1, len(x))
    out.shape = tuple(shp)
    helper.append_op(type="stack", inputs={"X": list(x)},
                     outputs={"Y": [out]}, attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    if num is None:
        num = x.shape[axis]
    outs = []
    for _ in range(num):
        o = helper.create_variable_for_type_inference(x.dtype)
        shp = list(x.shape)
        shp.pop(axis if axis >= 0 else len(shp) + axis)
        o.shape = tuple(shp)
        outs.append(o)
    helper.append_op(type="unstack", inputs={"X": [x]}, outputs={"Y": outs},
                     attrs={"axis": axis, "num": num})
    return outs


def unbind(input, axis=0):
    helper = LayerHelper("unbind")
    num = input.shape[axis]
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(num)]
    helper.append_op(type="unbind", inputs={"X": [input]},
                     outputs={"Out": outs}, attrs={"axis": axis})
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if not any(isinstance(t, Variable) for t in expand_times):
        out.shape = tuple(s * t if s > 0 else -1
                          for s, t in zip(x.shape, expand_times))
    helper.append_op(type="expand", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"expand_times": [t if not isinstance(t, Variable)
                                             else -1 for t in expand_times]})
    return out


def expand_as(x, target_tensor, name=None):
    helper = LayerHelper("expand_as", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = target_tensor.shape
    helper.append_op(type="expand_as",
                     inputs={"X": [x], "target_tensor": [target_tensor]},
                     outputs={"Out": [out]})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    shp = list(input.shape)
    ok = all(not isinstance(s, Variable) for s in list(starts) + list(ends))
    if ok:
        for ax, s, e in zip(axes, starts, ends):
            if shp[ax] < 0:
                continue
            d = shp[ax]
            s2 = max(s + d, 0) if s < 0 else min(s, d)
            e2 = max(e + d, 0) if e < 0 else min(e, d)
            shp[ax] = max(e2 - s2, 0)
        out.shape = tuple(shp)
    helper.append_op(type="slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends), "decrease_axis": [],
                            "infer_flags": [1] * len(axes)})
    return out


def strided_slice(input, axes, starts, ends, strides):
    helper = LayerHelper("strided_slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="strided_slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends), "strides": list(strides),
                            "decrease_axis": [],
                            "infer_flags": [1] * len(axes)})
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    idx_rows = index.shape[0] if index.shape else -1
    out.shape = tuple([idx_rows] + list(input.shape[1:]))
    helper.append_op(type="gather", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = tuple(list(index.shape[:-1])
                      + list(input.shape[index.shape[-1]:]))
    helper.append_op(type="gather_nd", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="scatter",
                     inputs={"X": [input], "Ids": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]}, attrs={"overwrite": overwrite})
    return out


def scatter_nd_add(ref, index, updates, name=None):
    helper = LayerHelper("scatter_nd_add", **locals())
    out = helper.create_variable_for_type_inference(ref.dtype)
    out.shape = ref.shape
    helper.append_op(type="scatter_nd_add",
                     inputs={"X": [ref], "Index": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]})
    return out


def scatter_nd(index, updates, shape, name=None):
    from .tensor import fill_constant
    zero = fill_constant(shape, updates.dtype, 0.0)
    return scatter_nd_add(zero, index, updates, name)


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.FP32)
    shp = list(input.shape)
    if shp and shp[-1] == 1:
        shp = shp[:-1]
    out.shape = tuple(shp + [depth if not isinstance(depth, Variable) else -1])
    inputs = {"X": [input]}
    attrs = {"allow_out_of_range": allow_out_of_range}
    if isinstance(depth, Variable):
        inputs["depth_tensor"] = [depth]
        attrs["depth"] = 1
    else:
        attrs["depth"] = depth
    helper.append_op(type="one_hot", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="norm", inputs={"X": [x]},
                     outputs={"Out": [out], "Norm": [norm]},
                     attrs={"axis": 1 if axis is None else axis,
                            "epsilon": epsilon})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="clip", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="clip_by_norm", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"max_norm": float(max_norm)})
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = (1,)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = tuple(list(x.shape[:x_num_col_dims])
                      + list(y.shape[y_num_col_dims:]))
    helper.append_op(type="mul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def _elementwise(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape if len(x.shape) >= len(y.shape) else y.shape
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    helper.kwargs["act"] = act
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_pow", x, y, axis, act, name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mod", x, y, axis, act, name)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_floordiv", x, y, axis, act, name)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    dtype = convert_np_dtype_to_dtype_(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    if not any(isinstance(s, Variable) for s in shape):
        out.shape = tuple(int(s) for s in shape)
    helper.append_op(type="uniform_random", outputs={"Out": [out]},
                     attrs={"shape": [int(s) for s in shape
                                      if not isinstance(s, Variable)],
                            "min": float(min), "max": float(max),
                            "seed": seed, "dtype": dtype})
    out.stop_gradient = True
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    dtype = convert_np_dtype_to_dtype_(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(int(s) for s in shape)
    helper.append_op(type="gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": [int(s) for s in shape],
                            "mean": float(mean), "std": float(std),
                            "seed": seed, "dtype": dtype})
    out.stop_gradient = True
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    out.shape = (_prod(x.shape[:axis]), _prod(x.shape[axis:]))
    helper.append_op(type="flatten2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": axis})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = tuple(s + paddings[2 * i] + paddings[2 * i + 1] if s >= 0 else -1
                      for i, s in enumerate(x.shape))
    helper.append_op(type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings),
                            "pad_value": float(pad_value)})
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="pad2d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": float(pad_value),
                            "data_format": data_format})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", **locals())
    out = helper.create_variable_for_type_inference(label.dtype)
    out.shape = label.shape
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(type="label_smooth", inputs=inputs,
                     outputs={"Out": [out]}, attrs={"epsilon": float(epsilon)})
    return out


def where(condition):
    helper = LayerHelper("where_index")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    helper.append_op(type="where_index", inputs={"Condition": [condition]},
                     outputs={"Out": [out]})
    return out


def sign(x):
    helper = LayerHelper("sign")
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="sign", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    helper = LayerHelper("shard_index")
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="shard_index", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"index_num": index_num, "nshards": nshards,
                            "shard_id": shard_id,
                            "ignore_value": ignore_value})
    return out


def relu(x, name=None):
    helper = LayerHelper("relu", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="relu", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def _logical(op_type, x, y, out=None, name=None):
    helper = LayerHelper(op_type, name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(VarDesc.VarType.BOOL)
        out.shape = x.shape
    ins = {"X": [x]} if y is None else {"X": [x], "Y": [y]}
    helper.append_op(type=op_type, inputs=ins, outputs={"Out": [out]})
    return out


def logical_and(x, y, out=None, name=None):
    return _logical("logical_and", x, y, out, name)


def logical_or(x, y, out=None, name=None):
    return _logical("logical_or", x, y, out, name)


def logical_xor(x, y, out=None, name=None):
    return _logical("logical_xor", x, y, out, name)


def logical_not(x, out=None, name=None):
    return _logical("logical_not", x, None, out, name)


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.INT32)
    out.shape = (len(input.shape),)
    helper.append_op(type="shape", inputs={"Input": [input]},
                     outputs={"Out": [out]})
    out.stop_gradient = True
    return out


def rank(input):
    from .tensor import assign
    return assign(np.asarray([len(input.shape)], np.int32))


def size(input):
    helper = LayerHelper("size")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    helper.append_op(type="size", inputs={"Input": [input]},
                     outputs={"Out": [out]})
    return out


def lod_reset(x, y=None, target_lod=None):
    """reference: layers/nn.py lod_reset — data unchanged, LoD replaced."""
    if y is None and target_lod is None:
        raise ValueError("lod_reset: either y or target_lod should be set")
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    if y is not None:
        inputs["Y"] = [y]
    helper.append_op(type="lod_reset", inputs=inputs,
                     outputs={"Out": [out]},
                     attrs={"target_lod": list(target_lod or [])})
    return out


def lod_append(x, level):
    helper = LayerHelper("lod_append")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="lod_append", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"level": list(level)})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    helper = LayerHelper("image_resize", **locals())
    op_type = {"BILINEAR": "bilinear_interp", "NEAREST": "nearest_interp",
               "TRILINEAR": "trilinear_interp"}[resample.upper()]
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"align_corners": align_corners, "align_mode": align_mode,
             "interp_method": op_type.split("_")[0],
             "data_layout": data_format}
    inputs = {"X": [input]}
    if out_shape is not None:
        if isinstance(out_shape, Variable):
            inputs["OutSize"] = [out_shape]
            attrs.update({"out_h": -1, "out_w": -1, "scale": 0.0})
        else:
            attrs.update({"out_h": int(out_shape[0]),
                          "out_w": int(out_shape[1]), "scale": 0.0})
            out.shape = (input.shape[0], input.shape[1],
                         int(out_shape[0]), int(out_shape[1]))
    else:
        attrs.update({"out_h": -1, "out_w": -1, "scale": float(scale)})
    helper.append_op(type=op_type, inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode, data_format)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True, data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners, 1, data_format)


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="grid_sampler",
                     inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]})
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    helper = LayerHelper("unfold", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="unfold", inputs={"X": [x]}, outputs={"Y": [out]},
                     attrs={"kernel_sizes": _pair(kernel_sizes),
                            "strides": _pair(strides),
                            "paddings": _pair(paddings, 4)
                            if isinstance(paddings, int) else list(paddings),
                            "dilations": _pair(dilations)})
    return out


def crop(x, shape=None, offsets=None, name=None):
    return crop_tensor(x, shape, offsets, name)


def crop_tensor(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop_tensor", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    attrs = {}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = [int(s) for s in shape]
        out.shape = tuple(attrs["shape"])
    if isinstance(offsets, (list, tuple)):
        attrs["offsets"] = [int(o) for o in offsets]
    elif offsets is None:
        attrs["offsets"] = [0] * len(x.shape)
    helper.append_op(type="crop_tensor", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def sum(x):
    from .tensor import sums
    return sums(x if isinstance(x, (list, tuple)) else [x])


def cast_(x, dtype):
    from .tensor import cast
    return cast(x, dtype)


def maxout(x, groups, name=None, axis=1):
    helper = LayerHelper("maxout", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="maxout", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"groups": groups, "axis": axis})
    return out


def space_to_depth(x, blocksize, name=None):
    helper = LayerHelper("space_to_depth", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="space_to_depth", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"blocksize": blocksize})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    helper = LayerHelper("affine_channel", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="affine_channel",
                     inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
                     outputs={"Out": [out]},
                     attrs={"data_layout": data_layout})
    return helper.append_activation(out)


def similarity_focus(input, axis, indexes, name=None):
    helper = LayerHelper("similarity_focus", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="similarity_focus", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"axis": axis, "indexes": list(indexes)})
    return out


def hash(input, hash_size, num_hash=1, name=None):
    helper = LayerHelper("hash", **locals())
    out = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    helper.append_op(type="hash", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"mod_by": hash_size, "num_hash": num_hash})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="log_loss",
                     inputs={"Predicted": [input], "Labels": [label]},
                     outputs={"Loss": [out]}, attrs={"epsilon": epsilon})
    return out


def add_position_encoding(input, alpha, beta, name=None):
    helper = LayerHelper("add_position_encoding", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="add_position_encoding", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"alpha": float(alpha), "beta": float(beta)})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", **locals())
    dtype = helper.input_dtype("x") if False else x.dtype
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[size, x.shape[1], y.shape[1]],
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = (x.shape[0], size)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if helper.bias_attr:
        b = helper.create_parameter(attr=helper.bias_attr, shape=[1, size],
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def merge_selected_rows(x, name=None):
    helper = LayerHelper("merge_selected_rows", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="merge_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def get_tensor_from_selected_rows(x, name=None):
    helper = LayerHelper("get_tensor_from_selected_rows", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="get_tensor_from_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    from .py_func_registry import register_callable
    helper = LayerHelper("py_func")
    fid = register_callable(func)
    bid = register_callable(backward_func) if backward_func else -1
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    helper.append_op(type="py_func", inputs={"X": list(xs)},
                     outputs={"Out": list(outs)},
                     attrs={"forward_callable_id": fid,
                            "backward_callable_id": bid})
    return out


def pixel_shuffle(x, upscale_factor):
    helper = LayerHelper("pixel_shuffle")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pixel_shuffle", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"upscale_factor": upscale_factor})
    return out


def fsp_matrix(x, y):
    helper = LayerHelper("fsp_matrix")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="fsp", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def continuous_value_model(input, cvm, use_cvm=True):
    helper = LayerHelper("cvm")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cvm", inputs={"X": [input], "CVM": [cvm]},
                     outputs={"Y": [out]}, attrs={"use_cvm": use_cvm})
    return out


def unique(x, dtype="int32"):
    helper = LayerHelper("unique")
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(
        convert_np_dtype_to_dtype_(dtype))
    helper.append_op(type="unique", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index]},
                     attrs={"dtype": convert_np_dtype_to_dtype_(dtype)})
    return out, index


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(
        convert_np_dtype_to_dtype_(dtype))
    count = helper.create_variable_for_type_inference(
        convert_np_dtype_to_dtype_(dtype))
    helper.append_op(type="unique_with_counts", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index],
                              "Count": [count]},
                     attrs={"dtype": convert_np_dtype_to_dtype_(dtype)})
    return out, index, count


interpolate = image_resize


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_variable_for_type_inference(x.dtype)
    loss = helper.create_variable_for_type_inference(x.dtype)
    loss.shape = (x.shape[0], 1)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(type="smooth_l1_loss", inputs=inputs,
                     outputs={"Diff": [diff], "Out": [loss]},
                     attrs={"sigma": sigma if sigma is not None else 1.0})
    return loss


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    out.shape = inputs[0].shape
    helper.append_op(type="multiplex",
                     inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def _act_layer(op_type, x, attrs=None, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs=attrs or {})
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", **locals())
    alpha_shape = [1]
    if mode == "channel":
        alpha_shape = [1, x.shape[1], 1, 1] if False else [x.shape[1]]
    elif mode == "element":
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(attr=helper.param_attr, shape=alpha_shape,
                                    dtype=x.dtype,
                                    default_initializer=Constant(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _act_layer("brelu", x, {"t_min": t_min, "t_max": t_max}, name)


def leaky_relu(x, alpha=0.02, name=None):
    return _act_layer("leaky_relu", x, {"alpha": alpha}, name)


def soft_relu(x, threshold=40.0, name=None):
    return _act_layer("soft_relu", x, {"threshold": threshold}, name)


def swish(x, beta=1.0, name=None):
    return _act_layer("swish", x, {"beta": beta}, name)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return _act_layer("hard_swish", x,
                      {"threshold": threshold, "scale": scale,
                       "offset": offset}, name)


def elu(x, alpha=1.0, name=None):
    return _act_layer("elu", x, {"alpha": alpha}, name)


def relu6(x, threshold=6.0, name=None):
    return _act_layer("relu6", x, {"threshold": threshold}, name)


def pow(x, factor=1.0, name=None):
    return _act_layer("pow", x, {"factor": factor}, name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _act_layer("stanh", x, {"scale_a": scale_a, "scale_b": scale_b},
                      name)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _act_layer("hard_sigmoid", x, {"slope": slope, "offset": offset},
                      name)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    """reference: layers/nn.py im2sequence — image patches to LoD sequence."""
    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)
    kernels = _pair(filter_size)
    strides = _pair(stride)
    pads = [padding] * 4 if isinstance(padding, int) else list(padding)
    if len(pads) == 2:
        pads = pads * 2
    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": kernels, "strides": strides,
                            "paddings": pads})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", **locals())
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[future_context_size + 1,
                                       input.shape[-1]],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="row_conv", inputs={"X": [input], "Filter": [w]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    helper = LayerHelper("global_step_counter")
    counter_name = counter_name or "@STEP_COUNTER@"
    counter = helper.create_or_get_global_variable(
        name=counter_name, dtype=VarDesc.VarType.INT64, shape=[1],
        persistable=True)
    if not getattr(counter, "_step_init", False):
        helper.set_variable_initializer(counter, Constant(float(begin - 1)))
        counter._step_init = True
        helper.main_program.global_block()._prepend_op(
            type="increment", inputs={"X": [counter]},
            outputs={"Out": [counter]}, attrs={"step": float(step)})
        counter.stop_gradient = True
    return counter


def roll(input, shifts, dims=None):
    helper = LayerHelper("roll")
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="roll", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"shifts": shifts if isinstance(shifts, list)
                            else [shifts],
                            "dims": dims if isinstance(dims, list)
                            else ([dims] if dims is not None else [])})
    return out


def index_select(input, index, dim=0):
    helper = LayerHelper("index_select")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="index_select",
                     inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]}, attrs={"dim": dim})
    return out


def index_sample(x, index):
    helper = LayerHelper("index_sample")
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = index.shape
    helper.append_op(type="index_sample",
                     inputs={"X": [x], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    helper = LayerHelper("temporal_shift", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="temporal_shift", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"seg_num": seg_num, "shift_ratio": shift_ratio})
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: layers/nn.py spectral_norm — power-iteration u/v state."""
    from ..initializer import Normal
    helper = LayerHelper("spectral_norm", **locals())
    dtype = weight.dtype
    h = weight.shape[dim]
    w_dims = 1
    for i, d in enumerate(weight.shape):
        if i != dim:
            w_dims *= d
    u = helper.create_parameter(attr=None, shape=[h], dtype=dtype,
                                default_initializer=Normal(0.0, 1.0))
    u.stop_gradient = True
    v = helper.create_parameter(attr=None, shape=[w_dims], dtype=dtype,
                                default_initializer=Normal(0.0, 1.0))
    v.stop_gradient = True
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = weight.shape
    helper.append_op(type="spectral_norm",
                     inputs={"Weight": [weight], "U": [u], "V": [v]},
                     outputs={"Out": [out]},
                     attrs={"dim": dim, "power_iters": power_iters,
                            "eps": eps})
    return out


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="random_crop", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"shape": list(shape),
                            "seed": int(seed) if seed else 0})
    return out


def linear_chain_crf(input, label, param_attr=None, length=None):
    """reference: layers/nn.py linear_chain_crf — CRF NLL; Transition rows
    [start; end; tags x tags]."""
    helper = LayerHelper("linear_chain_crf", **locals())
    dtype = helper.input_dtype()
    num_tags = input.shape[-1]
    transition = helper.create_parameter(attr=param_attr,
                                         shape=[num_tags + 2, num_tags],
                                         dtype=dtype)
    ll = helper.create_variable_for_type_inference(dtype)
    alpha = helper.create_variable_for_type_inference(dtype)
    e_exps = helper.create_variable_for_type_inference(dtype)
    t_exps = helper.create_variable_for_type_inference(dtype)
    ll.shape = (-1, 1)
    helper.append_op(
        type="linear_chain_crf",
        inputs={"Emission": [input], "Transition": [transition],
                "Label": [label]},
        outputs={"LogLikelihood": [ll], "Alpha": [alpha],
                 "EmissionExps": [e_exps], "TransitionExps": [t_exps]})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    from ..core import VarDesc
    helper = LayerHelper("crf_decoding", **locals())
    transition = helper.get_parameter(param_attr.name)
    path = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT64)
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [path]})
    return path


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """reference: layers/nn.py ctc_greedy_decoder — argmax then merge
    repeats + drop blanks (ctc_align)."""
    from ..core import VarDesc
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    # argmax over classes, keep LoD of input
    amax = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    helper.append_op(type="arg_max", inputs={"X": [input]},
                     outputs={"Out": [amax]},
                     attrs={"axis": -1, "keepdims": True})
    out = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    helper.append_op(type="ctc_align", inputs={"Input": [amax]},
                     outputs={"Output": [out]},
                     attrs={"blank": blank, "merge_repeated": True})
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    iou = helper.create_variable_for_type_inference(VarDesc.VarType.FP32)
    out_wrong = helper.create_variable_for_type_inference(VarDesc.VarType.INT32)
    out_correct = helper.create_variable_for_type_inference(VarDesc.VarType.INT32)
    helper.append_op(type="mean_iou",
                     inputs={"Predictions": [input], "Labels": [label]},
                     outputs={"OutMeanIou": [iou], "OutWrong": [out_wrong],
                              "OutCorrect": [out_correct]},
                     attrs={"num_classes": num_classes})
    return iou, out_wrong, out_correct


def dice_loss(input, label, epsilon=1e-5):
    from . import loss as _  # noqa
    label = one_hot(label, depth=input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = reduce_sum(input * label, dim=reduce_dims)
    dice_denominator = reduce_sum(input, dim=reduce_dims) + reduce_sum(
        label, dim=reduce_dims)
    dice_score = 1 - inse * 2 / (dice_denominator + epsilon)
    return mean(dice_score)


# --------------------------------------------------------------------------
# batch-2 wrappers (vision/misc ops — reference layers/nn.py same names)
# --------------------------------------------------------------------------
def cos_sim(X, Y):
    helper = LayerHelper("cos_sim", **locals())
    out = helper.create_variable_for_type_inference(X.dtype)
    xnorm = helper.create_variable_for_type_inference(X.dtype)
    ynorm = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(type="cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xnorm],
                              "YNorm": [ynorm]})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    helper = LayerHelper("lrn", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="lrn", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta,
                            "data_format": data_format})
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", **locals())
    out = helper.create_variable_for_type_inference(y.dtype)
    out.shape = x.shape
    helper.append_op(type="pad_constant_like",
                     inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
                     attrs={"pad_value": float(pad_value)})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_lod=None):
    helper = LayerHelper("roi_pool", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    argmax = helper.create_variable_for_type_inference(VarDesc.VarType.INT32)
    out.shape = (-1, input.shape[1], pooled_height, pooled_width)
    helper.append_op(type="roi_pool",
                     inputs={"X": [input], "ROIs": [rois]},
                     outputs={"Out": [out], "Argmax": [argmax]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None,
              rois_lod=None):
    helper = LayerHelper("roi_align", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = (-1, input.shape[1], pooled_height, pooled_width)
    helper.append_op(type="roi_align",
                     inputs={"X": [input], "ROIs": [rois]},
                     outputs={"Out": [out]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale,
                            "sampling_ratio": sampling_ratio})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    helper = LayerHelper("scale", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="scale", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like", **locals())
    out = helper.create_variable_for_type_inference(
        convert_np_dtype_to_dtype_(dtype))
    helper.append_op(type="uniform_random_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "min": float(min),
                            "max": float(max), "seed": seed,
                            "dtype": convert_np_dtype_to_dtype_(dtype),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random_batch_size_like", **locals())
    out = helper.create_variable_for_type_inference(
        convert_np_dtype_to_dtype_(dtype))
    helper.append_op(type="gaussian_random_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "mean": float(mean),
                            "std": float(std), "seed": seed,
                            "dtype": convert_np_dtype_to_dtype_(dtype),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """Greedy CTC decode: per-step argmax, merge repeats, drop blanks
    (reference layers/nn.py ctc_greedy_decoder:5116 → ctc_align_op.cc).
    LoD mode (input_length None): LoD [T, C] probs → LoD [Tout, 1] ids.
    Padding mode: [N, T, C] + input_length [N, 1] → (padded ids [N, T],
    output lengths [N, 1])."""
    helper = LayerHelper("ctc_greedy_decoder", **locals())
    _, idx = topk(input, k=1)
    ctc_out = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT64)
    if input_length is None:
        helper.append_op(type="ctc_align", inputs={"Input": [idx]},
                         outputs={"Output": [ctc_out]},
                         attrs={"merge_repeated": True, "blank": blank})
        ctc_out.shape = (-1, 1)
        return ctc_out
    ctc_out_len = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT64)
    ctc_input = squeeze(idx, [2])
    helper.append_op(type="ctc_align",
                     inputs={"Input": [ctc_input],
                             "InputLength": [input_length]},
                     outputs={"Output": [ctc_out],
                              "OutputLength": [ctc_out_len]},
                     attrs={"merge_repeated": True, "blank": blank,
                            "padding_value": padding_value})
    ctc_out.shape = tuple(input.shape[:-1])
    ctc_out_len.shape = (-1, 1)
    return ctc_out, ctc_out_len


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("sampling_id", **locals())
    out = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    out.shape = tuple(x.shape[:-1])  # one drawn id per distribution row
    helper.append_op(type="sampling_id", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"min": min, "max": max, "seed": seed})
    return out


def shuffle_channel(x, group, name=None):
    helper = LayerHelper("shuffle_channel", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="shuffle_channel", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"group": group})
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    helper = LayerHelper("adaptive_pool3d", **locals())
    ksize = _pair(pool_size, 3)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    out.shape = (input.shape[0], input.shape[1]) + tuple(ksize)
    if require_index:
        if pool_type != "max":
            raise ValueError("require_index needs pool_type='max'")
        mask = helper.create_variable_for_type_inference(
            VarDesc.VarType.INT32)
        mask.shape = out.shape
        helper.append_op(
            type="max_pool3d_with_index", inputs={"X": [input]},
            outputs={"Out": [out], "Mask": [mask]},
            attrs={"ksize": ksize, "adaptive": True,
                   "strides": [1, 1, 1], "paddings": [0, 0, 0]})
        return out, mask
    helper.append_op(
        type="pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": ksize, "adaptive": True,
               "strides": [1, 1, 1], "paddings": [0, 0, 0],
               "global_pooling": False, "data_format": "NCDHW",
               "padding_algorithm": "EXPLICIT"})
    return out


def inplace_abn(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
                param_attr=None, bias_attr=None, data_layout="NCHW",
                name=None, moving_mean_name=None, moving_variance_name=None,
                do_model_average_for_mean_and_var=True,
                use_global_stats=False, act_alpha=1.0):
    """batch_norm fused with an in-place activation (reference
    inplace_abn_op.cc; memory aliasing is XLA's concern on TPU)."""
    helper = LayerHelper("inplace_abn", **locals())
    dtype = helper.input_dtype()
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale_p = helper.create_parameter(attr=helper.param_attr, shape=[c],
                                      dtype=dtype,
                                      default_initializer=Constant(1.0))
    bias_p = helper.create_parameter(attr=helper.bias_attr, shape=[c],
                                     dtype=dtype, is_bias=True)
    mean = helper.create_parameter(
        attr=ParamAttr(name=moving_mean_name, initializer=Constant(0.0),
                       trainable=False), shape=[c], dtype=dtype)
    mean.stop_gradient = True
    variance = helper.create_parameter(
        attr=ParamAttr(name=moving_variance_name, initializer=Constant(1.0),
                       trainable=False), shape=[c], dtype=dtype)
    variance.stop_gradient = True
    saved_mean = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input.shape
    helper.append_op(
        type="inplace_abn",
        inputs={"X": [input], "Scale": [scale_p], "Bias": [bias_p],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout,
               "use_global_stats": use_global_stats,
               "activation": act or "identity", "alpha": act_alpha})
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    helper = LayerHelper("conv3d_transpose", **locals())
    dtype = helper.input_dtype()
    groups = groups or 1
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    padding = _pair(padding, 3)
    in_c = input.shape[1]
    if filter_size is None:
        assert output_size is not None
        output_size = _pair(output_size, 3)
        filter_size = [
            (output_size[i] - (input.shape[2 + i] - 1) * stride[i]
             + 2 * padding[i] - 1) // dilation[i] + 1 for i in (0, 1, 2)]
    else:
        filter_size = _pair(filter_size, 3)
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[in_c, num_filters // groups] + list(filter_size), dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = (input.shape[0], num_filters) + tuple(
        output_size if output_size else (
            (input.shape[2 + i] - 1) * stride[i] - 2 * padding[i]
            + dilation[i] * (filter_size[i] - 1) + 1 for i in (0, 1, 2)))
    helper.append_op(
        type="conv3d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups, "use_cudnn": use_cudnn,
               "output_size": list(_pair(output_size, 3)) if output_size
               else [],
               "padding_algorithm": "EXPLICIT", "data_format": data_format})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    helper = LayerHelper("resize_trilinear", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"align_corners": align_corners, "align_mode": align_mode,
             "interp_method": "trilinear", "data_layout": data_format}
    inputs = {"X": [input]}
    if out_shape is not None:
        if isinstance(out_shape, Variable):
            inputs["OutSize"] = [out_shape]
            attrs.update({"out_d": -1, "out_h": -1, "out_w": -1,
                          "scale": 0.0})
        else:
            attrs.update({"out_d": int(out_shape[0]),
                          "out_h": int(out_shape[1]),
                          "out_w": int(out_shape[2]), "scale": 0.0})
            out.shape = (input.shape[0], input.shape[1]) + tuple(
                int(s) for s in out_shape)
    else:
        attrs.update({"out_d": -1, "out_h": -1, "out_w": -1,
                      "scale": float(scale)})
    helper.append_op(type="trilinear_interp", inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len, keeping aspect
    (reference layers/nn.py image_resize_short)."""
    in_shape = input.shape
    hw = in_shape[2:4]
    short_idx = hw.index(min(hw))
    out_shape = list(hw)
    out_shape[short_idx] = out_short_len
    out_shape[1 - short_idx] = int(
        round(hw[1 - short_idx] * out_short_len / hw[short_idx]))
    return image_resize(input, out_shape=out_shape, resample=resample)


def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", **locals())
    out = helper.create_variable_for_type_inference(theta.dtype)
    inputs = {"Theta": [theta]}
    attrs = {"align_corners": True}
    if isinstance(out_shape, Variable):
        inputs["OutputShape"] = [out_shape]
    else:
        attrs["output_shape"] = [int(s) for s in out_shape]
        out.shape = (out_shape[0], out_shape[2], out_shape[3], 2)
    helper.append_op(type="affine_grid", inputs=inputs,
                     outputs={"Output": [out]}, attrs=attrs)
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    helper = LayerHelper("psroi_pool", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = (-1, output_channels, pooled_height, pooled_width)
    helper.append_op(type="psroi_pool",
                     inputs={"X": [input], "ROIs": [rois]},
                     outputs={"Out": [out]},
                     attrs={"output_channels": output_channels,
                            "spatial_scale": spatial_scale,
                            "pooled_height": pooled_height,
                            "pooled_width": pooled_width})
    return out


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    helper = LayerHelper("prroi_pool", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if batch_roi_nums is not None:
        inputs["BatchRoINums"] = [batch_roi_nums]
    out.shape = (-1, input.shape[1], pooled_height, pooled_width)
    helper.append_op(type="prroi_pool", inputs=inputs,
                     outputs={"Out": [out]},
                     attrs={"spatial_scale": spatial_scale,
                            "pooled_height": pooled_height,
                            "pooled_width": pooled_width})
    return out


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=None,
                    deformable_groups=None, im2col_step=None,
                    param_attr=None, bias_attr=None, modulated=True,
                    name=None):
    helper = LayerHelper("deformable_conv", **locals())
    dtype = helper.input_dtype()
    groups = groups or 1
    deformable_groups = deformable_groups or 1
    filter_size = _pair(filter_size)
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[num_filters, input.shape[1] // groups] + list(filter_size),
        dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    st, pd, dl = _pair(stride), _pair(padding), _pair(dilation)
    out.shape = (input.shape[0], num_filters) + tuple(
        (input.shape[2 + i] + 2 * pd[i] - (dl[i] * (filter_size[i] - 1) + 1))
        // st[i] + 1 for i in (0, 1))
    attrs = {"strides": _pair(stride), "paddings": _pair(padding),
             "dilations": _pair(dilation), "groups": groups,
             "deformable_groups": deformable_groups,
             "im2col_step": im2col_step or 64}
    if modulated and mask is None:
        raise ValueError(
            "deformable_conv: mask is required when modulated=True "
            "(pass modulated=False for the v1 op)")
    if modulated:
        helper.append_op(
            type="deformable_conv",
            inputs={"Input": [input], "Offset": [offset], "Mask": [mask],
                    "Filter": [w]},
            outputs={"Output": [out]}, attrs=attrs)
    else:
        helper.append_op(
            type="deformable_conv_v1",
            inputs={"Input": [input], "Offset": [offset], "Filter": [w]},
            outputs={"Output": [out]}, attrs=attrs)
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=[1, 1],
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=False,
                           name=None):
    helper = LayerHelper("deformable_roi_pooling", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    top_count = helper.create_variable_for_type_inference(input.dtype)
    part_size = part_size or [pooled_height, pooled_width]
    output_dim = (input.shape[1] // (group_size[0] * group_size[1])
                  if position_sensitive else input.shape[1])
    helper.append_op(
        type="deformable_psroi_pooling",
        inputs={"Input": [input], "ROIs": [rois], "Trans": [trans]},
        outputs={"Output": [out], "TopCount": [top_count]},
        attrs={"no_trans": no_trans, "spatial_scale": spatial_scale,
               "output_dim": output_dim, "group_size": list(group_size),
               "pooled_height": pooled_height, "pooled_width": pooled_width,
               "part_size": list(part_size),
               "sample_per_part": sample_per_part, "trans_std": trans_std})
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    helper = LayerHelper("chunk_eval", **locals())
    f32 = VarDesc.VarType.FP32
    i64 = VarDesc.VarType.INT64
    precision = helper.create_variable_for_type_inference(f32)
    recall = helper.create_variable_for_type_inference(f32)
    f1_score = helper.create_variable_for_type_inference(f32)
    num_infer = helper.create_variable_for_type_inference(i64)
    num_label = helper.create_variable_for_type_inference(i64)
    num_correct = helper.create_variable_for_type_inference(i64)
    inputs = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        inputs["SeqLength"] = [seq_length]
    helper.append_op(
        type="chunk_eval", inputs=inputs,
        outputs={"Precision": [precision], "Recall": [recall],
                 "F1-Score": [f1_score], "NumInferChunks": [num_infer],
                 "NumLabelChunks": [num_label],
                 "NumCorrectChunks": [num_correct]},
        attrs={"num_chunk_types": num_chunk_types,
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": list(excluded_chunk_types or [])})
    return (precision, recall, f1_score, num_infer, num_label, num_correct)


def filter_by_instag(ins, ins_tag, filter_tag, is_lod, out_val_if_empty=0):
    helper = LayerHelper("filter_by_instag", **locals())
    out = helper.create_variable_for_type_inference(ins.dtype)
    loss_weight = helper.create_variable_for_type_inference(
        VarDesc.VarType.FP32)
    index_map = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT64)
    helper.append_op(
        type="filter_by_instag",
        inputs={"Ins": [ins], "Ins_tag": [ins_tag],
                "Filter_tag": [filter_tag]},
        outputs={"Out": [out], "LossWeight": [loss_weight],
                 "IndexMap": [index_map]},
        attrs={"is_lod": is_lod, "out_val_if_empty": out_val_if_empty})
    return out, loss_weight, index_map
