"""Generated activation-style layer wrappers (reference:
python/paddle/fluid/layers/ops.py via layer_function_generator.py)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = [
    "sigmoid", "logsigmoid", "exp", "tanh", "atan", "tanh_shrink",
    "softshrink", "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "acos",
    "asin", "sin", "sinh", "cosh", "round", "reciprocal", "square",
    "softplus", "softsign", "erf", "gelu", "hard_shrink", "thresholded_relu",
    "log", "log1p", "cumsum", "selu",
]


def _make_act(op_type, extra_attrs=()):
    def layer(x, name=None, **kwargs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        out.shape = x.shape
        attrs = {k: kwargs[k] for k in extra_attrs if k in kwargs}
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out
    layer.__name__ = op_type
    return layer


sigmoid = _make_act("sigmoid")
logsigmoid = _make_act("logsigmoid")
exp = _make_act("exp")
tanh = _make_act("tanh")
atan = _make_act("atan")
tanh_shrink = _make_act("tanh_shrink")
softshrink = _make_act("softshrink", ("lambda",))
sqrt = _make_act("sqrt")
rsqrt = _make_act("rsqrt")
abs = _make_act("abs")
ceil = _make_act("ceil")
floor = _make_act("floor")
cos = _make_act("cos")
acos = _make_act("acos")
asin = _make_act("asin")
sin = _make_act("sin")
sinh = _make_act("sinh")
cosh = _make_act("cosh")
round = _make_act("round")
reciprocal = _make_act("reciprocal")
square = _make_act("square")
softplus = _make_act("softplus")
softsign = _make_act("softsign")
erf = _make_act("erf")
gelu = _make_act("gelu", ("approximate",))
hard_shrink = _make_act("hard_shrink", ("threshold",))
thresholded_relu = _make_act("thresholded_relu", ("threshold",))
log = _make_act("log")
log1p = _make_act("log1p")
selu = _make_act("selu", ("scale", "alpha"))


def cumsum(x, axis=-1, exclusive=False, reverse=False, name=None):
    helper = LayerHelper("cumsum", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="cumsum", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis, "exclusive": exclusive,
                            "reverse": reverse})
    return out
