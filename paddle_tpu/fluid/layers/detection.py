"""Detection layers (reference: python/paddle/fluid/layers/detection.py —
prior_box:~, density_prior_box, multi_box_head, bipartite_match,
target_assign, detection_output, ssd_loss, anchor_generator,
generate_proposals, yolo_box, yolov3_loss, multiclass_nms, box_coder,
box_clip, distribute/collect_fpn_proposals). Kernels in
ops/detection_ops.py: geometry is pure jnp; NMS/matching are host ops."""
from __future__ import annotations

from ..core import VarDesc
from ..layer_helper import LayerHelper

__all__ = [
    "prior_box", "density_prior_box", "multi_box_head", "bipartite_match",
    "target_assign", "detection_output", "ssd_loss", "rpn_target_assign",
    "retinanet_target_assign", "sigmoid_focal_loss", "anchor_generator",
    "roi_perspective_transform", "generate_proposal_labels",
    "generate_proposals", "generate_mask_labels", "iou_similarity",
    "box_coder", "polygon_box_transform", "yolov3_loss", "yolo_box",
    "box_clip", "multiclass_nms", "locality_aware_nms",
    "retinanet_detection_output", "distribute_fpn_proposals",
    "box_decoder_and_assign", "collect_fpn_proposals",
    "detection_map",
]


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="polygon_box_transform",
                     inputs={"Input": [input]},
                     outputs={"Output": [out]})
    return out


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    helper = LayerHelper("rpn_target_assign", **locals())
    i32 = VarDesc.VarType.INT32
    loc_index = _mk_out(helper, i32)
    score_index = _mk_out(helper, i32)
    loc_index.shape = (-1,)
    score_index.shape = (-1,)
    target_label = _mk_out(helper, i32)
    target_bbox = _mk_out(helper)
    bbox_inside_weight = _mk_out(helper)
    helper.append_op(
        type="rpn_target_assign",
        inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
                "IsCrowd": [is_crowd], "ImInfo": [im_info]},
        outputs={"LocationIndex": [loc_index], "ScoreIndex": [score_index],
                 "TargetLabel": [target_label], "TargetBBox": [target_bbox],
                 "BBoxInsideWeight": [bbox_inside_weight]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "rpn_fg_fraction": rpn_fg_fraction,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "use_random": use_random})
    from .nn import gather as _gather, reshape as _reshape
    pred_loc = _gather(_reshape(bbox_pred, [-1, 4]), loc_index)
    pred_score = _gather(_reshape(cls_logits, [-1, 1]), score_index)
    return (pred_score, pred_loc, target_label, target_bbox,
            bbox_inside_weight)


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd, im_info,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    helper = LayerHelper("retinanet_target_assign", **locals())
    i32 = VarDesc.VarType.INT32
    loc_index = _mk_out(helper, i32)
    score_index = _mk_out(helper, i32)
    loc_index.shape = (-1,)
    score_index.shape = (-1,)
    target_label = _mk_out(helper, i32)
    target_bbox = _mk_out(helper)
    bbox_inside_weight = _mk_out(helper)
    fg_num = _mk_out(helper, i32)
    helper.append_op(
        type="retinanet_target_assign",
        inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
                "GtLabels": [gt_labels], "IsCrowd": [is_crowd],
                "ImInfo": [im_info]},
        outputs={"LocationIndex": [loc_index], "ScoreIndex": [score_index],
                 "TargetLabel": [target_label], "TargetBBox": [target_bbox],
                 "BBoxInsideWeight": [bbox_inside_weight],
                 "ForegroundNumber": [fg_num]},
        attrs={"positive_overlap": positive_overlap,
               "negative_overlap": negative_overlap})
    from .nn import gather as _gather, reshape as _reshape
    pred_loc = _gather(_reshape(bbox_pred, [-1, 4]), loc_index)
    pred_score = _gather(_reshape(cls_logits, [-1, num_classes]),
                         score_index)
    return (pred_score, pred_loc, target_label, target_bbox,
            bbox_inside_weight, fg_num)


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    helper = LayerHelper("retinanet_detection_output", **locals())
    out = _mk_out(helper)
    helper.append_op(
        type="retinanet_detection_output",
        inputs={"BBoxes": list(bboxes), "Scores": list(scores),
                "Anchors": list(anchors), "ImInfo": [im_info]},
        outputs={"Out": [out]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "nms_eta": nms_eta})
    return out


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    helper = LayerHelper("locality_aware_nms", **locals())
    out = _mk_out(helper)
    helper.append_op(
        type="locality_aware_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "normalized": normalized, "nms_eta": nms_eta,
               "background_label": background_label})
    return out


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip, name=None):
    helper = LayerHelper("box_decoder_and_assign", **locals())
    decoded = _mk_out(helper)
    assigned = _mk_out(helper)
    helper.append_op(
        type="box_decoder_and_assign",
        inputs={"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                "TargetBox": [target_box], "BoxScore": [box_score]},
        outputs={"DecodeBox": [decoded], "OutputAssignBox": [assigned]},
        attrs={"box_clip": box_clip})
    return decoded, assigned


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=[0.1, 0.1, 0.2, 0.2],
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    helper = LayerHelper("generate_proposal_labels", **locals())
    rois = _mk_out(helper)
    labels_int32 = _mk_out(helper, VarDesc.VarType.INT32)
    bbox_targets = _mk_out(helper)
    bbox_inside_weights = _mk_out(helper)
    bbox_outside_weights = _mk_out(helper)
    helper.append_op(
        type="generate_proposal_labels",
        inputs={"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
                "IsCrowd": [is_crowd], "GtBoxes": [gt_boxes],
                "ImInfo": [im_info]},
        outputs={"Rois": [rois], "LabelsInt32": [labels_int32],
                 "BboxTargets": [bbox_targets],
                 "BboxInsideWeights": [bbox_inside_weights],
                 "BboxOutsideWeights": [bbox_outside_weights]},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": bbox_reg_weights,
               "class_nums": class_nums or 81, "use_random": use_random,
               "is_cls_agnostic": is_cls_agnostic,
               "is_cascade_rcnn": is_cascade_rcnn})
    return (rois, labels_int32, bbox_targets, bbox_inside_weights,
            bbox_outside_weights)


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    helper = LayerHelper("generate_mask_labels", **locals())
    mask_rois = _mk_out(helper)
    roi_has_mask_int32 = _mk_out(helper, VarDesc.VarType.INT32)
    mask_int32 = _mk_out(helper, VarDesc.VarType.INT32)
    helper.append_op(
        type="generate_mask_labels",
        inputs={"ImInfo": [im_info], "GtClasses": [gt_classes],
                "IsCrowd": [is_crowd], "GtSegms": [gt_segms],
                "Rois": [rois], "LabelsInt32": [labels_int32]},
        outputs={"MaskRois": [mask_rois],
                 "RoiHasMaskInt32": [roi_has_mask_int32],
                 "MaskInt32": [mask_int32]},
        attrs={"num_classes": num_classes, "resolution": resolution})
    return mask_rois, roi_has_mask_int32, mask_int32


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    helper = LayerHelper("roi_perspective_transform", **locals())
    out = _mk_out(helper)
    mask = _mk_out(helper, VarDesc.VarType.INT32)
    matrix = _mk_out(helper)
    out2in_idx = _mk_out(helper, VarDesc.VarType.INT32)
    out2in_w = _mk_out(helper)
    helper.append_op(
        type="roi_perspective_transform",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out], "Mask": [mask], "TransformMatrix": [matrix],
                 "Out2InIdx": [out2in_idx], "Out2InWeights": [out2in_w]},
        attrs={"transformed_height": transformed_height,
               "transformed_width": transformed_width,
               "spatial_scale": spatial_scale})
    return out, mask, matrix


def _mk_out(helper, dtype=None):
    return helper.create_variable_for_type_inference(
        dtype or VarDesc.VarType.FP32)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    box = _mk_out(helper)
    var = _mk_out(helper)
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [box], "Variances": [var]},
        attrs={"min_sizes": list(map(float, min_sizes)),
               "max_sizes": list(map(float, max_sizes or [])),
               "aspect_ratios": list(map(float, aspect_ratios)),
               "variances": list(map(float, variance)),
               "flip": flip, "clip": clip, "step_w": float(steps[0]),
               "step_h": float(steps[1]), "offset": offset,
               "min_max_aspect_ratios_order": min_max_aspect_ratios_order})
    return box, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=[0.1, 0.1, 0.2, 0.2],
                      clip=False, steps=[0.0, 0.0], offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper("density_prior_box", name=name)
    box = _mk_out(helper)
    var = _mk_out(helper)
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [box], "Variances": [var]},
        attrs={"densities": list(map(int, densities or [])),
               "fixed_sizes": list(map(float, fixed_sizes or [])),
               "fixed_ratios": list(map(float, fixed_ratios or [])),
               "variances": list(map(float, variance)), "clip": clip,
               "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": offset, "flatten_to_2d": flatten_to_2d})
    return box, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=[0.1, 0.1, 0.2, 0.2], stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchor = _mk_out(helper)
    var = _mk_out(helper)
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchor], "Variances": [var]},
        attrs={"anchor_sizes": list(map(float, anchor_sizes
                                        or [64., 128., 256., 512.])),
               "aspect_ratios": list(map(float, aspect_ratios
                                         or [0.5, 1.0, 2.0])),
               "variances": list(map(float, variance)),
               "stride": list(map(float, stride or [16., 16.])),
               "offset": offset})
    return anchor, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    helper = LayerHelper("box_coder", name=name)
    output = _mk_out(helper)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    from ..framework import Variable
    if isinstance(prior_box_var, Variable):
        inputs["PriorBoxVar"] = [prior_box_var]
    elif isinstance(prior_box_var, (list, tuple)):
        attrs["variance"] = list(map(float, prior_box_var))
    helper.append_op(type="box_coder", inputs=inputs,
                     outputs={"OutputBox": [output]}, attrs=attrs)
    return output


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    output = _mk_out(helper)
    helper.append_op(type="box_clip",
                     inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [output]})
    return output


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper("bipartite_match", name=name)
    match_indices = _mk_out(helper, VarDesc.VarType.INT32)
    match_distance = _mk_out(helper)
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [match_indices],
                 "ColToRowMatchDist": [match_distance]},
        attrs={"match_type": match_type or "bipartite",
               "dist_threshold": dist_threshold or 0.5})
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper("target_assign", name=name)
    out = _mk_out(helper, input.dtype)
    out_weight = _mk_out(helper)
    helper.append_op(
        type="target_assign",
        inputs={"X": [input], "MatchIndices": [matched_indices]},
        outputs={"Out": [out], "OutWeight": [out_weight]},
        attrs={"mismatch_value": mismatch_value or 0})
    return out, out_weight


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    helper = LayerHelper("multiclass_nms", name=name)
    output = _mk_out(helper)
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [output]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "normalized": normalized, "nms_eta": nms_eta,
               "background_label": background_label})
    return output


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_index=False):
    """reference layers/detection.py detection_output: decode + NMS."""
    from .nn import transpose
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = transpose(scores, [0, 2, 1])  # [N, C, M]
    return multiclass_nms(decoded, scores_t, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold, True, nms_eta,
                          background_label)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = _mk_out(helper)
    scores = _mk_out(helper)
    helper.append_op(
        type="yolo_box", inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes], "Scores": [scores]},
        attrs={"anchors": list(map(int, anchors)), "class_num": class_num,
               "conf_thresh": conf_thresh,
               "downsample_ratio": downsample_ratio,
               "clip_bbox": clip_bbox})
    return boxes, scores


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    helper = LayerHelper("yolov3_loss", name=name)
    loss = _mk_out(helper)
    inputs = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        inputs["GTScore"] = [gt_score]
    helper.append_op(
        type="yolov3_loss", inputs=inputs, outputs={"Loss": [loss]},
        attrs={"anchors": list(map(int, anchors)),
               "anchor_mask": list(map(int, anchor_mask)),
               "class_num": class_num, "ignore_thresh": ignore_thresh,
               "downsample_ratio": downsample_ratio,
               "use_label_smooth": use_label_smooth})
    return loss


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None,
                       return_rois_num=False):
    helper = LayerHelper("generate_proposals", name=name)
    rois = _mk_out(helper)
    roi_probs = _mk_out(helper)
    rois_num = _mk_out(helper, VarDesc.VarType.INT32)
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rois], "RpnRoiProbs": [roi_probs],
                 "RpnRoisNum": [rois_num]},
        attrs={"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
               "nms_thresh": nms_thresh, "min_size": min_size, "eta": eta})
    if return_rois_num:
        return rois, roi_probs, rois_num
    return rois, roi_probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n = max_level - min_level + 1
    outs = [_mk_out(helper) for _ in range(n)]
    restore = _mk_out(helper, VarDesc.VarType.INT32)
    helper.append_op(
        type="distribute_fpn_proposals", inputs={"FpnRois": [fpn_rois]},
        outputs={"MultiFpnRois": outs, "RestoreIndex": [restore]},
        attrs={"min_level": min_level, "max_level": max_level,
               "refer_level": refer_level, "refer_scale": refer_scale})
    return outs, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    helper = LayerHelper("collect_fpn_proposals", name=name)
    output = _mk_out(helper)
    helper.append_op(
        type="collect_fpn_proposals",
        inputs={"MultiLevelRois": list(multi_rois),
                "MultiLevelScores": list(multi_scores)},
        outputs={"FpnRois": [output]},
        attrs={"post_nms_topN": post_nms_top_n})
    return output


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True,
             sample_size=None):
    """SSD multibox loss (reference detection.py ssd_loss): match priors
    to gt by IoU, localization smooth-L1 on matched priors + softmax conf
    loss (hard-negative mining simplified to the matched/unmatched split)."""
    import paddle_tpu.fluid.layers as nn
    from .loss import softmax_with_cross_entropy
    iou = iou_similarity(gt_box, prior_box)          # LoD [T, M]
    matched, _dist = bipartite_match(iou, match_type, neg_overlap)
    # location targets: per-prior encoded gt (target_assign gathers the
    # matched row of the [T, M, 4] encoding)
    enc_gt = box_coder(prior_box, prior_box_var or [0.1, 0.1, 0.2, 0.2],
                       gt_box)                        # [T, M, 4]
    loc_tgt, loc_w = target_assign(enc_gt, matched)   # [N, M, 4], [N, M, 1]
    lbl_tgt, _lbl_w = target_assign(gt_label, matched,
                                    mismatch_value=background_label)
    conf_loss = softmax_with_cross_entropy(
        confidence, nn.cast(lbl_tgt, "int64"))        # [N, M, 1]
    # per-prior huber on the 4 coords: 0.5*min(|d|,1)^2 + (|d| - min(|d|,1))
    d = location - nn.cast(loc_tgt, "float32")
    ad = nn.abs(d)
    c = nn.clip(ad, 0.0, 1.0)
    huber = c * c * 0.5 + (ad - c)
    loc_l = nn.reduce_sum(huber, dim=-1, keep_dim=True)  # [N, M, 1]
    loss = (conf_loss * conf_loss_weight
            + nn.elementwise_mul(loc_l, loc_w) * loc_loss_weight)
    return loss


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = _mk_out(helper, x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"box_normalized": box_normalized})
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    helper = LayerHelper("sigmoid_focal_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="sigmoid_focal_loss",
                     inputs={"X": [x], "Label": [label], "FgNum": [fg_num]},
                     outputs={"Out": [out]},
                     attrs={"gamma": gamma, "alpha": alpha})
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head over multiple feature maps (reference
    detection.py multi_box_head): per input, conv to loc/conf + priors;
    outputs concatenated over maps."""
    from . import nn
    from .nn import conv2d, transpose, reshape
    from .tensor import concat
    n_layer = len(inputs)
    if min_sizes is None:
        # reference ratio schedule
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n_layer - 2))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes
    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        ms = [ms] if not isinstance(ms, (list, tuple)) else list(ms)
        mx = max_sizes[i] if max_sizes else None
        mx = ([mx] if mx is not None and
              not isinstance(mx, (list, tuple)) else mx)
        ar = aspect_ratios[i]
        ar = [ar] if not isinstance(ar, (list, tuple)) else list(ar)
        stp = steps[i] if steps else [step_w or 0.0, step_h or 0.0]
        if not isinstance(stp, (list, tuple)):
            stp = [stp, stp]
        box, var = prior_box(feat, image, ms, mx, ar, variance, flip, clip,
                             stp, offset)
        num_priors = 1 if not hasattr(box, "shape") else None
        # priors per cell = len(ms)*len(ar expanded) + len(mx)
        n_ar = 1 + sum(2 if flip and abs(a - 1.0) > 1e-6 else 1
                       for a in ar if abs(a - 1.0) > 1e-6)
        num_priors = len(ms) * n_ar + (len(mx) if mx else 0)
        loc = conv2d(feat, num_priors * 4, kernel_size, stride, pad)
        conf = conv2d(feat, num_priors * num_classes, kernel_size, stride,
                      pad)
        locs.append(reshape(transpose(loc, [0, 2, 3, 1]), [0, -1, 4]))
        confs.append(reshape(transpose(conf, [0, 2, 3, 1]),
                             [0, -1, num_classes]))
        boxes_l.append(reshape(box, [-1, 4]))
        vars_l.append(reshape(var, [-1, 4]))
    mbox_locs = concat(locs, axis=1)
    mbox_confs = concat(confs, axis=1)
    boxes = concat(boxes_l, axis=0)
    variances = concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral"):
    helper = LayerHelper("detection_map", **locals())
    map_out = helper.create_variable_for_type_inference(VarDesc.VarType.FP32)
    pos_count = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT32)
    true_pos = helper.create_variable_for_type_inference(VarDesc.VarType.FP32)
    false_pos = helper.create_variable_for_type_inference(
        VarDesc.VarType.FP32)
    inputs = {"DetectRes": [detect_res], "Label": [label]}
    if has_state is not None:
        inputs["HasState"] = [has_state]
    if input_states is not None:
        inputs["PosCount"] = [input_states[0]]
        inputs["TruePos"] = [input_states[1]]
        inputs["FalsePos"] = [input_states[2]]
    if out_states is not None:
        pos_count, true_pos, false_pos = out_states
    helper.append_op(
        type="detection_map", inputs=inputs,
        outputs={"MAP": [map_out], "AccumPosCount": [pos_count],
                 "AccumTruePos": [true_pos], "AccumFalsePos": [false_pos]},
        attrs={"overlap_threshold": overlap_threshold,
               "class_num": class_num, "background_label": background_label,
               "evaluate_difficult": evaluate_difficult,
               "ap_type": ap_version})
    return map_out
