"""Detection layers (reference: python/paddle/fluid/layers/detection.py —
prior_box, yolo_box, multiclass_nms, …). Round-1: API surface present;
kernels land with the detection batch (these are host/inference-side ops,
not on the training hot path)."""
from __future__ import annotations

__all__ = [
    "prior_box", "density_prior_box", "multi_box_head", "bipartite_match",
    "target_assign", "detection_output", "ssd_loss", "rpn_target_assign",
    "retinanet_target_assign", "sigmoid_focal_loss", "anchor_generator",
    "roi_perspective_transform", "generate_proposal_labels",
    "generate_proposals", "generate_mask_labels", "iou_similarity",
    "box_coder", "polygon_box_transform", "yolov3_loss", "yolo_box",
    "box_clip", "multiclass_nms", "locality_aware_nms",
    "retinanet_detection_output", "distribute_fpn_proposals",
    "box_decoder_and_assign", "collect_fpn_proposals",
]


def _nyi(name):
    def fn(*a, **k):
        raise NotImplementedError(f"{name}: detection batch pending")
    fn.__name__ = name
    return fn


for _n in __all__:
    globals()[_n] = _nyi(_n)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    from ..layer_helper import LayerHelper
    helper = LayerHelper("sigmoid_focal_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="sigmoid_focal_loss",
                     inputs={"X": [x], "Label": [label], "FgNum": [fg_num]},
                     outputs={"Out": [out]},
                     attrs={"gamma": gamma, "alpha": alpha})
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    from ..layer_helper import LayerHelper
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"box_normalized": box_normalized})
    return out
