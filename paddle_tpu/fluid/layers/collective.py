"""Collective op-builder helpers (reference:
python/paddle/fluid/layers/collective.py — _allreduce:20, _c_allreduce:64,
_c_broadcast:93 …). The c_* ops map ring_id → a named mesh axis and lower to
XLA ICI collectives (see paddle_tpu/ops/collective_ops.py)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["_allreduce", "_broadcast", "_c_allreduce", "_c_broadcast",
           "_c_allgather", "_c_reducescatter", "_c_sync_calc_stream",
           "_c_sync_comm_stream"]


def _allreduce(x, out=None, reduce_type="sum", sync_mode=False):
    helper = LayerHelper("allreduce")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
        out.shape = x.shape
    helper.append_op(type="allreduce", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"reduce_type": {"sum": 0, "prod": 1, "max": 2,
                                            "min": 3}[reduce_type],
                            "sync_mode": sync_mode})
    return out


def _broadcast(x, root, sync_mode=False):
    helper = LayerHelper("broadcast")
    helper.append_op(type="broadcast", inputs={"X": [x]},
                     outputs={"Out": [x]},
                     attrs={"sync_mode": sync_mode, "root": root})
    return x


def _c_allreduce(x, out=None, reduce_type="sum", ring_id=0,
                 use_calc_stream=False):
    helper = LayerHelper("c_allreduce")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
        out.shape = x.shape
    helper.append_op(type=f"c_allreduce_{reduce_type}",
                     inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"ring_id": ring_id,
                            "use_calc_stream": use_calc_stream})
    return out


def _c_broadcast(x, root=0, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_broadcast")
    helper.append_op(type="c_broadcast", inputs={"X": [x]},
                     outputs={"Out": [x]},
                     attrs={"root": root, "ring_id": ring_id,
                            "use_calc_stream": use_calc_stream})
    return x


def _c_allgather(x, nranks, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_allgather")
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape:
        out.shape = tuple([x.shape[0] * nranks] + list(x.shape[1:]))
    helper.append_op(type="c_allgather", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"nranks": nranks, "ring_id": ring_id,
                            "use_calc_stream": use_calc_stream})
    return out


def _c_reducescatter(x, nranks, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_reducescatter")
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape:
        out.shape = tuple([x.shape[0] // nranks] + list(x.shape[1:]))
    helper.append_op(type="c_reducescatter", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"nranks": nranks, "ring_id": ring_id,
                            "use_calc_stream": use_calc_stream})
    return out


def _c_sync_calc_stream(x):
    helper = LayerHelper("c_sync_calc_stream")
    helper.append_op(type="c_sync_calc_stream", inputs={"X": [x]},
                     outputs={"Out": [x]})
    return x


def _c_sync_comm_stream(x, ring_id=0):
    helper = LayerHelper("c_sync_comm_stream")
    helper.append_op(type="c_sync_comm_stream", inputs={"X": [x]},
                     outputs={"Out": [x]}, attrs={"ring_id": ring_id})
    return x
