"""Probability distributions (reference:
python/paddle/fluid/layers/distributions.py)."""
from __future__ import annotations

import math

__all__ = ["Uniform", "Normal", "Categorical", "MultivariateNormalDiag"]


class Distribution:
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, shape, seed=0):
        from .nn import uniform_random
        from .tensor import cast
        return uniform_random(shape, min=0.0, max=1.0, seed=seed) \
            * (self.high - self.low) + self.low

    def log_prob(self, value):
        from . import ops
        from .tensor import fill_constant
        rng = self.high - self.low
        return 0.0 - ops.log(value * 0.0 + rng)

    def entropy(self):
        from . import ops
        return ops.log(self.high - self.low)


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc, self.scale = loc, scale

    def sample(self, shape, seed=0):
        from .nn import gaussian_random
        return gaussian_random(shape, mean=0.0, std=1.0, seed=seed) \
            * self.scale + self.loc

    def log_prob(self, value):
        from . import ops
        var = self.scale * self.scale
        return -1.0 * ((value - self.loc) * (value - self.loc)) / (2.0 * var) \
            - ops.log(self.scale) - math.log(math.sqrt(2.0 * math.pi))

    def entropy(self):
        from . import ops
        return 0.5 + 0.5 * math.log(2.0 * math.pi) + ops.log(self.scale)


class Categorical(Distribution):
    """reference distributions.py Categorical — entropy + KL over logits."""

    def __init__(self, logits):
        self.logits = logits

    def _probs(self):
        from .nn import softmax
        return softmax(self.logits)

    def entropy(self):
        from .nn import reduce_sum
        from . import ops
        p = self._probs()
        return 0.0 - reduce_sum(p * ops.log(p + 1e-10), dim=-1)

    def kl_divergence(self, other):
        from .nn import reduce_sum
        from . import ops
        p = self._probs()
        q = other._probs()
        return reduce_sum(p * (ops.log(p + 1e-10) - ops.log(q + 1e-10)),
                          dim=-1)


class MultivariateNormalDiag(Distribution):
    """reference distributions.py MultivariateNormalDiag — diagonal-scale
    gaussian; entropy + KL (scale is the [D, D] diagonal matrix like the
    reference, only its diagonal participates)."""

    def __init__(self, loc, scale):
        self.loc, self.scale = loc, scale

    def entropy(self):
        import math as _m
        from .nn import reduce_sum
        from . import ops
        # 0.5 * (D * (1 + log(2π)) + log det Σ), Σ = scale²
        d = float(self.loc.shape[-1])
        logdet = reduce_sum(ops.log(_diag_part(self.scale) + 1e-10), dim=-1)
        return 0.5 * d * (1.0 + _m.log(2.0 * _m.pi)) + logdet

    def kl_divergence(self, other):
        from .nn import reduce_sum
        from . import ops
        s1 = _diag_part(self.scale)
        s2 = _diag_part(other.scale)
        var1, var2 = s1 * s1, s2 * s2
        mu = other.loc - self.loc
        return 0.5 * (reduce_sum(var1 / var2, dim=-1)
                      + reduce_sum(mu * mu / var2, dim=-1)
                      - float(self.loc.shape[-1])
                      + 2.0 * (reduce_sum(ops.log(s2 + 1e-10), dim=-1)
                               - reduce_sum(ops.log(s1 + 1e-10), dim=-1)))


def _diag_part(mat):
    """Diagonal of the trailing [D, D] block via elementwise mask-sum."""
    from .nn import reduce_sum
    from .tensor import eye
    d = int(mat.shape[-1])
    return reduce_sum(mat * eye(d, dtype=mat.dtype), dim=-1)
