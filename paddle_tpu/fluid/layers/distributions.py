"""Probability distributions (reference:
python/paddle/fluid/layers/distributions.py)."""
from __future__ import annotations

import math

__all__ = ["Uniform", "Normal", "Categorical", "MultivariateNormalDiag"]


class Distribution:
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, shape, seed=0):
        from .nn import uniform_random
        from .tensor import cast
        return uniform_random(shape, min=0.0, max=1.0, seed=seed) \
            * (self.high - self.low) + self.low

    def log_prob(self, value):
        from . import ops
        from .tensor import fill_constant
        rng = self.high - self.low
        return 0.0 - ops.log(value * 0.0 + rng)

    def entropy(self):
        from . import ops
        return ops.log(self.high - self.low)


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc, self.scale = loc, scale

    def sample(self, shape, seed=0):
        from .nn import gaussian_random
        return gaussian_random(shape, mean=0.0, std=1.0, seed=seed) \
            * self.scale + self.loc

    def log_prob(self, value):
        from . import ops
        var = self.scale * self.scale
        return -1.0 * ((value - self.loc) * (value - self.loc)) / (2.0 * var) \
            - ops.log(self.scale) - math.log(math.sqrt(2.0 * math.pi))

    def entropy(self):
        from . import ops
        return 0.5 + 0.5 * math.log(2.0 * math.pi) + ops.log(self.scale)


class Categorical(Distribution):
    def __init__(self, logits):
        self.logits = logits

    def entropy(self):
        from .nn import softmax, reduce_sum
        from . import ops
        p = softmax(self.logits)
        return 0.0 - reduce_sum(p * ops.log(p + 1e-10), dim=-1)


class MultivariateNormalDiag(Distribution):
    def __init__(self, loc, scale):
        self.loc, self.scale = loc, scale
