"""Control-flow layers (reference: python/paddle/fluid/layers/control_flow.py
— While:3739, cond, increment, array ops, comparison wrappers)."""
from __future__ import annotations

import numpy as np

from .. import unique_name
from ..core import VarDesc
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper

__all__ = [
    "While", "Switch", "increment", "array_write", "create_array",
    "less_than", "less_equal", "greater_than", "greater_equal", "equal",
    "not_equal", "array_read", "array_length", "cond", "IfElse",
    "StaticRNN", "Print", "Assert", "is_empty", "case", "switch_case",
    "while_loop", "DynamicRNN", "reorder_lod_tensor_by_rank",
    "lod_rank_table", "max_sequence_len", "lod_tensor_to_array",
    "array_to_lod_tensor", "shrink_memory",
]


def _cmp(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference(VarDesc.VarType.BOOL)
        cond.stop_gradient = True
        cond.shape = x.shape
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, force_cpu=None, cond=None):
    return _cmp("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _cmp("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _cmp("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _cmp("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _cmp("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _cmp("not_equal", x, y, cond)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
        out.shape = x.shape
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def create_array(dtype):
    helper = LayerHelper("array")
    return helper.main_program.current_block().create_var(
        name="{}.out".format(helper.name),
        type=VarDesc.VarType.LOD_TENSOR_ARRAY, dtype=dtype)


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(type="write_to_array",
                     inputs={"X": [x], "I": [i]}, outputs={"Out": [array]})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(type="read_from_array",
                     inputs={"X": [array], "I": [i]}, outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    out.stop_gradient = True
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out


class While:
    """while loop over a sub-block (reference control_flow.py While)."""

    def __init__(self, cond, is_test=False, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.is_test = is_test

    class _BlockGuard:
        def __init__(self, while_obj):
            self.w = while_obj

        def __enter__(self):
            self.w._main = default_main_program()
            self.w._block = self.w._main._create_block()
            return self.w._block

        def __exit__(self, exc_type, exc_val, exc_tb):
            if exc_type is not None:
                return False
            main = self.w._main
            sub_block = main.current_block()
            main._rollback()
            parent = main.current_block()
            x_names = set()
            inner_outputs = {self.w.cond_var.name}
            for op in sub_block.ops:
                for name in op.input_arg_names:
                    if name not in inner_outputs:
                        x_names.add(name)
                inner_outputs.update(op.output_arg_names)
            out_vars = [n for n in inner_outputs
                        if parent.has_var_recursive(n)]
            step_scope = parent.create_var(
                type=VarDesc.VarType.STEP_SCOPES,
                name=self.w.helper.name + ".step_scopes")
            parent.append_op(
                type="while",
                inputs={"X": sorted(x_names), "Condition": [self.w.cond_var]},
                outputs={"Out": sorted(out_vars),
                         "StepScopes": [step_scope]},
                attrs={"sub_block": sub_block, "is_test": self.w.is_test})
            return True

    def block(self):
        return While._BlockGuard(self)


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """functional while (reference control_flow.py:3739 while_loop)."""
    pre_cond = cond(*loop_vars)
    w = While(pre_cond, is_test, name)
    with w.block():
        new_vars = body(*loop_vars)
        if not isinstance(new_vars, (list, tuple)):
            new_vars = [new_vars]
        from .tensor import assign
        for old, new in zip(loop_vars, new_vars):
            assign(new, old)
        new_cond = cond(*loop_vars)
        assign(new_cond, pre_cond)
    return loop_vars


def cond(pred, true_fn=None, false_fn=None, name=None):
    """two-branch conditional via conditional_block + select (reference
    control_flow.py cond)."""
    helper = LayerHelper("cond", name=name)
    main = default_main_program()
    from .tensor import cast, fill_constant
    from .nn import logical_not

    def _run_branch(fn, cond_var):
        block = main._create_block()
        out = fn() if fn is not None else None
        sub = main.current_block()
        main._rollback()
        parent = main.current_block()
        inner_out = set()
        x_names = set()
        for op in sub.ops:
            for n in op.input_arg_names:
                if n not in inner_out:
                    x_names.add(n)
            inner_out.update(op.output_arg_names)
        scope_var = parent.create_var(
            type=VarDesc.VarType.STEP_SCOPES,
            name=helper.name + ".branch_scope")
        parent.append_op(
            type="conditional_block",
            inputs={"Cond": [cond_var], "Input": sorted(x_names)},
            outputs={"Out": sorted(inner_out), "Scope": [scope_var]},
            attrs={"sub_block": sub, "is_scalar_condition": True})
        return out

    true_out = _run_branch(true_fn, pred)
    not_pred = logical_not(pred)
    false_out = _run_branch(false_fn, not_pred)
    if true_out is None and false_out is None:
        return None

    def _promote(v, like):
        """Host scalar branch outputs (e.g. the early-exit transformer's
        `flag = True`) become constants so select_input can pick between
        a Variable and a literal."""
        if isinstance(v, Variable) or not isinstance(v, (bool, int, float)):
            return v
        if isinstance(like, Variable):
            dt = like.dtype
        elif isinstance(v, bool):
            dt = VarDesc.VarType.BOOL
        elif isinstance(v, int):
            dt = VarDesc.VarType.INT64
        else:
            dt = VarDesc.VarType.FP32
        return fill_constant([1], dt, v)

    def _select(t, f):
        t = _promote(t, f)
        f = _promote(f, t)
        if not isinstance(t, Variable) and not isinstance(f, Variable):
            return t  # both host-side: branches agree structurally
        mask = cast(pred, VarDesc.VarType.INT32)
        o = helper.create_variable_for_type_inference(t.dtype)
        o.shape = t.shape
        helper.append_op(type="select_input",
                         inputs={"X": [f, t], "Mask": [mask]},
                         outputs={"Out": [o]})
        return o

    if isinstance(true_out, (list, tuple)):
        return [_select(t, f) for t, f in zip(true_out, false_out)]
    return _select(true_out, false_out)


def case(pred_fn_pairs, default=None, name=None):
    """reference control_flow.py case — chained cond."""
    pred, fn = pred_fn_pairs[0]
    if len(pred_fn_pairs) == 1:
        return cond(pred, fn, default, name)
    return cond(pred, fn, lambda: case(pred_fn_pairs[1:], default), name)


def switch_case(branch_index, branch_fns, default=None, name=None):
    from .tensor import fill_constant
    pairs = []
    for idx, fn in (branch_fns.items() if isinstance(branch_fns, dict)
                    else enumerate(branch_fns)):
        c = fill_constant([1], branch_index.dtype, idx)
        pairs.append((equal(branch_index, c), fn))
    return case(pairs, default, name)


class Switch:
    """reference control_flow.py Switch — used by lr schedulers."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.pre_not_conditions = []

    class _CaseGuard:
        def __init__(self, switch, cond_var):
            self.switch = switch
            self.cond_var = cond_var
            self.main = None

        def __enter__(self):
            from .nn import logical_and, logical_not
            self.main = default_main_program()
            s = self.switch
            if self.cond_var is not None:
                c = self.cond_var
                for nc in s.pre_not_conditions:
                    c = logical_and(c, nc)
                s.pre_not_conditions.append(logical_not(self.cond_var))
            else:
                c = None
                for i, nc in enumerate(s.pre_not_conditions):
                    c = nc if c is None else logical_and(c, nc)
            self.run_cond = c
            self.block = self.main._create_block()
            return self.block

        def __exit__(self, exc_type, exc_val, exc_tb):
            if exc_type is not None:
                return False
            main = self.main
            sub = main.current_block()
            main._rollback()
            parent = main.current_block()
            inner_out = set()
            x_names = set()
            for op in sub.ops:
                for n in op.input_arg_names:
                    if n not in inner_out:
                        x_names.add(n)
                inner_out.update(op.output_arg_names)
            scope_var = parent.create_var(
                type=VarDesc.VarType.STEP_SCOPES,
                name=self.switch.helper.name + ".case_scope")
            parent.append_op(
                type="conditional_block",
                inputs={"Cond": [self.run_cond], "Input": sorted(x_names)},
                outputs={"Out": sorted(inner_out), "Scope": [scope_var]},
                attrs={"sub_block": sub, "is_scalar_condition": True})
            return True

    def case(self, condition):
        return Switch._CaseGuard(self, condition)

    def default(self):
        return Switch._CaseGuard(self, None)

    def __enter__(self):
        return self

    def __exit__(self, *args):
        return False


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape  # reference print_op InferShape ShareDim
    helper.append_op(type="print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"first_n": first_n, "message": message or "",
                            "summarize": summarize,
                            "print_tensor_name": print_tensor_name,
                            "print_tensor_type": print_tensor_type,
                            "print_tensor_shape": print_tensor_shape,
                            "print_tensor_lod": print_tensor_lod,
                            "print_phase": print_phase.upper()})
    return out


def Assert(cond, data=None, summarize=20, name=None):
    helper = LayerHelper("assert", name=name)
    helper.append_op(type="assert",
                     inputs={"Cond": [cond],
                             "Data": list(data) if data else []},
                     outputs={}, attrs={"summarize": summarize})


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference(VarDesc.VarType.BOOL)
        cond.stop_gradient = True
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [cond]})
    return cond


class StaticRNN:
    """Fixed-length RNN over time-major input (reference:
    control_flow.py StaticRNN:336 — the reference records a step sub-block
    executed by the recurrent op; here the recorded step ops are UNROLLED
    across time with per-step var renaming, which XLA then rolls back into
    efficient code — compiler-friendly static control flow).

    with rnn.step():
        x_t = rnn.step_input(x)          # x: [T, batch, ...]
        prev = rnn.memory(shape=[-1, H], batch_ref=x_t)
        h = some_layers(x_t, prev)
        rnn.update_memory(prev, h)
        rnn.step_output(h)
    out = rnn()                          # [T, batch, ...]
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._block = self.helper.main_program.current_block()
        self._step_inputs = []     # (placeholder_var, source_var)
        self._memories = []        # dicts: placeholder, init_name, link
        self._step_outputs = []    # placeholder names
        self._template = None
        self._seq_len = None
        self._outputs = None
        self._in_step = False

    # ------------------------------------------------------------- API
    def step(self):
        rnn = self

        class _Guard:
            def __enter__(self):
                rnn._in_step = True
                rnn._n0 = len(rnn._block.ops)
                return rnn

            def __exit__(self, *exc):
                rnn._in_step = False
                if exc[0] is None:
                    rnn._complete()
                return False
        return _Guard()

    def _check_in_step(self):
        if not self._in_step:
            raise ValueError("StaticRNN: call inside 'with rnn.step():'")

    def step_input(self, x):
        self._check_in_step()
        if self._seq_len is None:
            self._seq_len = int(x.shape[0])
        elif int(x.shape[0]) != self._seq_len:
            raise ValueError("StaticRNN: step inputs disagree on seq_len")
        ph = self._block.create_var(
            name=unique_name.generate("static_rnn_x"),
            dtype=x.dtype, shape=tuple(x.shape[1:]))
        self._step_inputs.append((ph, x))
        return ph

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        self._check_in_step()
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError(
                    "StaticRNN.memory: need init or (shape, batch_ref)")
            from .tensor import fill_constant_batch_size_like
            # build the init OUTSIDE the recorded template, referencing the
            # SOURCE sequence var (a step placeholder has no runtime value;
            # the source is time-major so its batch dim is ref_batch_dim_idx)
            src_ref = batch_ref
            dim_idx = 0
            for ph2, src in self._step_inputs:
                if ph2.name == batch_ref.name:
                    src_ref = src
                    dim_idx = ref_batch_dim_idx
                    break
            ops_before = self._block.ops[self._n0:]
            del self._block.ops[self._n0:]
            init = fill_constant_batch_size_like(
                src_ref, [-1] + [int(s) for s in shape if s != -1],
                "float32", init_value, input_dim_idx=dim_idx,
                output_dim_idx=0)
            init_ops = self._block.ops[self._n0:]
            del self._block.ops[self._n0:]
            self._block.ops[self._n0:self._n0] = init_ops
            self._n0 += len(init_ops)
            self._block.ops.extend(ops_before)
        ph = self._block.create_var(
            name=unique_name.generate("static_rnn_mem"),
            dtype=init.dtype, shape=tuple(init.shape))
        self._memories.append({"ph": ph.name, "init": init.name,
                               "link": None})
        return ph

    def update_memory(self, mem, var):
        self._check_in_step()
        for m in self._memories:
            if m["ph"] == mem.name:
                m["link"] = var.name
                return
        raise ValueError("StaticRNN.update_memory: unknown memory")

    def step_output(self, o):
        self._check_in_step()
        self._step_outputs.append(o.name)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    # --------------------------------------------------------- unrolling
    def _complete(self):
        block = self._block
        template = block.ops[self._n0:]
        del block.ops[self._n0:]
        if self._seq_len is None:
            raise ValueError("StaticRNN: no step_input given")
        T = self._seq_len
        from ..framework import Operator
        collected = {name: [] for name in self._step_outputs}
        mem_cur = {m["ph"]: m["init"] for m in self._memories}
        for t in range(T):
            rename = dict(mem_cur)
            # slice step inputs: x[t]
            for ph, src in self._step_inputs:
                st = block.create_var(
                    name=unique_name.generate(f"{ph.name}@{t}"),
                    dtype=ph.dtype, shape=tuple(ph.shape))
                block.append_op(
                    type="slice", inputs={"Input": [src]},
                    outputs={"Out": [st]},
                    attrs={"axes": [0], "starts": [t], "ends": [t + 1],
                           "decrease_axis": [0]})
                rename[ph.name] = st.name
            # clone template ops with per-step output renaming
            for op in template:
                new_out = {}
                for slot, names in op.outputs.items():
                    outs = []
                    for n in names:
                        nn = f"{n}@t{t}"
                        src_v = block.vars.get(n)
                        if src_v is not None and nn not in block.vars:
                            block.create_var(name=nn, dtype=src_v.dtype,
                                             shape=tuple(src_v.shape))
                        rename[n] = nn
                        outs.append(nn)
                    new_out[slot] = outs
                new_in = {slot: [rename.get(n, n) for n in names]
                          for slot, names in op.inputs.items()}
                block.ops.append(Operator(block, op.type, inputs=new_in,
                                          outputs=new_out,
                                          attrs=dict(op.attrs)))
            for name in self._step_outputs:
                collected[name].append(rename.get(name, name))
            mem_cur = {m["ph"]: rename.get(m["link"], m["link"])
                       for m in self._memories if m["link"]}
        # stack step outputs back to [T, ...]
        from .nn import stack
        outs = []
        for name in self._step_outputs:
            vars_t = [block.vars[n] if n in block.vars else
                      self._var_of(n) for n in collected[name]]
            outs.append(stack(vars_t, axis=0))
        self._outputs = outs
        # the step placeholders and the template's original output vars
        # only existed for recording — after the per-step renaming no op
        # references them; drop them so the program carries no dead var
        # descs (the verifier's dead-var rule keys on exactly this)
        used = set()
        for op in block.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
        scratch = {ph.name for ph, _src in self._step_inputs}
        scratch |= {m["ph"] for m in self._memories}
        for op in template:
            scratch.update(op.output_arg_names)
        for name in scratch - used:
            v = block.vars.get(name)
            if v is not None and not v.persistable:
                del block.vars[name]

    def _var_of(self, name):
        v = self._block.vars.get(name)
        if v is None:
            raise KeyError(f"StaticRNN: var {name} missing")
        return v

    def __call__(self, *args):
        if self._outputs is None:
            raise ValueError("StaticRNN: use inside step() first")
        if len(self._outputs) == 1:
            return self._outputs[0]
        return self._outputs


def lod_rank_table(x, level=0):
    """reference control_flow.py lod_rank_table — sort sequences of one
    LoD level by length descending into a LoDRankTable var."""
    helper = LayerHelper("lod_rank_table")
    table = helper.main_program.current_block().create_var(
        name=unique_name.generate("lod_rank_table"),
        type=VarDesc.VarType.LOD_RANK_TABLE)
    table.stop_gradient = True
    helper.append_op(type="lod_rank_table", inputs={"X": [x]},
                     outputs={"Out": [table]}, attrs={"level": level})
    return table


def max_sequence_len(rank_table):
    helper = LayerHelper("max_seqence_length")
    res = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    helper.append_op(type="max_sequence_len",
                     inputs={"RankTable": [rank_table]},
                     outputs={"Out": [res]})
    return res


def lod_tensor_to_array(x, table):
    helper = LayerHelper("lod_tensor_to_array")
    array = helper.main_program.current_block().create_var(
        name=unique_name.generate("lod_tensor_to_array"),
        type=VarDesc.VarType.LOD_TENSOR_ARRAY, dtype=x.dtype)
    helper.append_op(type="lod_tensor_to_array",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [array]})
    return array


def array_to_lod_tensor(x, table):
    helper = LayerHelper("array_to_lod_tensor")
    tmp = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="array_to_lod_tensor",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [tmp]})
    return tmp


def shrink_memory(x, i, table):
    helper = LayerHelper("shrink_memory")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="shrink_rnn_memory",
                     inputs={"X": [x], "I": [i], "RankTable": [table]},
                     outputs={"Out": [out]})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reorder_lod_tensor_by_rank",
                     inputs={"X": [x], "RankTable": [rank_table]},
                     outputs={"Out": [out]})
    return out


class DynamicRNN:
    """Variable-length RNN over LoD sequences (reference control_flow.py
    DynamicRNN:2854): sequences are rank-sorted by length, split into
    per-timestep batches, and a While block walks the steps; memories
    shrink to the still-alive prefix each step."""

    BEFORE_RNN, IN_RNN, AFTER_RNN = 0, 1, 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self.lod_rank_table = None
        self.max_seq_len = None
        self.step_idx = None
        self.zero_idx = None
        self.mem_dict = {}
        self.output_array = []
        self.outputs = []
        self.cond = self.helper.create_variable_for_type_inference(
            VarDesc.VarType.BOOL)
        self.cond.stop_gradient = True
        self.while_op = While(self.cond)
        self.input_array = []
        self.mem_link = []

    def _parent_block_(self):
        prog = self.helper.main_program
        return prog.block(prog.current_block().parent_idx)

    def _assert_in_rnn_block_(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError(f"{method}() must be called inside block()")

    def _init_zero_idx_(self):
        if self.zero_idx is None:
            parent = self._parent_block_()
            self.zero_idx = parent.create_var(
                name=unique_name.generate("zero_idx"),
                dtype=VarDesc.VarType.INT64)
            parent.append_op(type="fill_constant",
                             inputs={}, outputs={"Out": [self.zero_idx]},
                             attrs={"shape": [1], "value": 0.0,
                                    "dtype": VarDesc.VarType.INT64,
                                    "force_cpu": True})

    def step_input(self, x, level=0):
        self._assert_in_rnn_block_("step_input")
        parent = self._parent_block_()
        if self.lod_rank_table is None:
            self.lod_rank_table = parent.create_var(
                name=unique_name.generate("lod_rank_table"),
                type=VarDesc.VarType.LOD_RANK_TABLE)
            self.lod_rank_table.stop_gradient = True
            parent.append_op(type="lod_rank_table", inputs={"X": [x]},
                             outputs={"Out": [self.lod_rank_table]},
                             attrs={"level": level})
            self.max_seq_len = parent.create_var(
                name=unique_name.generate("dynamic_rnn_max_seq_len"),
                dtype=VarDesc.VarType.INT64)
            parent.append_op(type="max_sequence_len",
                             inputs={"RankTable": [self.lod_rank_table]},
                             outputs={"Out": [self.max_seq_len]})
            parent.append_op(type="less_than",
                             inputs={"X": [self.step_idx],
                                     "Y": [self.max_seq_len]},
                             outputs={"Out": [self.cond]},
                             attrs={"force_cpu": True})
        input_array = parent.create_var(
            name=unique_name.generate("dynamic_rnn_input_array"),
            type=VarDesc.VarType.LOD_TENSOR_ARRAY, dtype=x.dtype)
        self.input_array.append((input_array, x.dtype))
        parent.append_op(type="lod_tensor_to_array",
                         inputs={"X": [x],
                                 "RankTable": [self.lod_rank_table]},
                         outputs={"Out": [input_array]})
        return array_read(input_array, self.step_idx)

    def static_input(self, x):
        self._assert_in_rnn_block_("static_input")
        if self.lod_rank_table is None:
            raise RuntimeError("static_input() needs step_input() first")
        parent = self._parent_block_()
        reordered = parent.create_var(
            name=unique_name.generate("dynamic_rnn_static_input_reordered"),
            dtype=x.dtype)
        parent.append_op(type="reorder_lod_tensor_by_rank",
                         inputs={"X": [x],
                                 "RankTable": [self.lod_rank_table]},
                         outputs={"Out": [reordered]})
        return shrink_memory(reordered, self.step_idx, self.lod_rank_table)

    def block(self):
        drnn = self

        class _Guard:
            def __enter__(self):
                if drnn.status != DynamicRNN.BEFORE_RNN:
                    raise ValueError("rnn.block() can only be entered once")
                from .tensor import fill_constant
                drnn.step_idx = fill_constant(shape=[1], dtype="int64",
                                              value=0, force_cpu=True)
                drnn.status = DynamicRNN.IN_RNN
                drnn._while_guard = drnn.while_op.block()
                drnn._while_guard.__enter__()
                return self

            def __exit__(self, et, ev, tb):
                if et is not None:
                    return False
                increment(drnn.step_idx, value=1.0, in_place=True)
                for new_mem, mem_array in drnn.mem_link:
                    array_write(new_mem, i=drnn.step_idx, array=mem_array)
                less_than(drnn.step_idx, drnn.max_seq_len, cond=drnn.cond)
                drnn._while_guard.__exit__(None, None, None)
                drnn.status = DynamicRNN.AFTER_RNN
                for arr in drnn.output_array:
                    drnn.outputs.append(
                        array_to_lod_tensor(arr, drnn.lod_rank_table))
                return False
        return _Guard()

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32"):
        self._assert_in_rnn_block_("memory")
        self._init_zero_idx_()
        parent = self._parent_block_()
        if init is not None:
            init_tensor = init
            if need_reorder and self.lod_rank_table is None:
                raise ValueError(
                    "memory(init=..., need_reorder=True) requires "
                    "step_input() to be called first")
            if need_reorder:
                reordered = parent.create_var(
                    name=unique_name.generate("dyn_rnn_mem_init_reordered"),
                    dtype=init.dtype)
                parent.append_op(
                    type="reorder_lod_tensor_by_rank",
                    inputs={"X": [init_tensor],
                            "RankTable": [self.lod_rank_table]},
                    outputs={"Out": [reordered]})
                init_tensor = reordered
            mem_array = parent.create_var(
                name=unique_name.generate("dynamic_rnn_mem_array"),
                type=VarDesc.VarType.LOD_TENSOR_ARRAY, dtype=init.dtype)
            parent.append_op(type="write_to_array",
                             inputs={"X": [init_tensor],
                                     "I": [self.zero_idx]},
                             outputs={"Out": [mem_array]})
        else:
            if not self.input_array:
                raise ValueError("step_input() must precede "
                                 "memory(shape=..., value=...)")
            arr, in_dtype = self.input_array[0]
            in0 = parent.create_var(name=unique_name.generate("in0"),
                                    dtype=in_dtype)
            parent.append_op(type="read_from_array",
                             inputs={"X": [arr], "I": [self.zero_idx]},
                             outputs={"Out": [in0]})
            from ..core import convert_np_dtype_to_dtype_
            init = parent.create_var(
                name=unique_name.generate("mem_init"),
                dtype=convert_np_dtype_to_dtype_(dtype))
            parent.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [in0]}, outputs={"Out": [init]},
                attrs={"shape": [-1] + list(shape), "value": float(value),
                       "dtype": convert_np_dtype_to_dtype_(dtype),
                       "input_dim_idx": 0, "output_dim_idx": 0})
            mem_array = parent.create_var(
                name=unique_name.generate("dynamic_rnn_mem_array"),
                type=VarDesc.VarType.LOD_TENSOR_ARRAY, dtype=init.dtype)
            parent.append_op(type="write_to_array",
                             inputs={"X": [init], "I": [self.zero_idx]},
                             outputs={"Out": [mem_array]})
        retv = array_read(mem_array, self.step_idx)
        retv = shrink_memory(retv, self.step_idx, self.lod_rank_table)
        self.mem_dict[retv.name] = mem_array
        return retv

    def update_memory(self, ex_mem, new_mem):
        self._assert_in_rnn_block_("update_memory")
        mem_array = self.mem_dict.get(ex_mem.name)
        if mem_array is None:
            raise ValueError("update_memory: ex_mem is not a memory()")
        self.mem_link.append((new_mem, mem_array))

    def output(self, *outputs):
        self._assert_in_rnn_block_("output")
        parent = self._parent_block_()
        for o in outputs:
            arr = parent.create_var(
                name=unique_name.generate("dynamic_rnn_output_array"),
                type=VarDesc.VarType.LOD_TENSOR_ARRAY, dtype=o.dtype)
            self.output_array.append(arr)
            array_write(o, i=self.step_idx, array=arr)

    def __call__(self, *args, **kwargs):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError("DynamicRNN outputs are available after "
                             "block() exits")
        return self.outputs[0] if len(self.outputs) == 1 else self.outputs


class IfElse:
    """Row-wise branching on a bool mask (reference control_flow.py IfElse):
    input() splits rows by cond into the active branch, output() records
    branch results, and __call__ merges them back in row order via
    merge_lod_tensor."""

    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.input_table = {}
        self.status = IfElse.OUT_IF_ELSE_BLOCKS
        # outputs per branch, keyed output position -> {branch: var}
        self._branch_outputs = {True: [], False: []}

    class _Branch:
        def __init__(self, ie, is_true):
            self.ie = ie
            self.is_true = is_true

        def __enter__(self):
            self.ie.status = (IfElse.IN_IF_ELSE_TRUE_BLOCKS if self.is_true
                              else IfElse.IN_IF_ELSE_FALSE_BLOCKS)
            return self

        def __exit__(self, et, ev, tb):
            self.ie.status = IfElse.OUT_IF_ELSE_BLOCKS
            return False

    def true_block(self):
        return IfElse._Branch(self, True)

    def false_block(self):
        return IfElse._Branch(self, False)

    def input(self, x):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("input() must be inside true_block/false_block")
        if x.name not in self.input_table:
            helper = self.helper
            t = helper.create_variable_for_type_inference(x.dtype)
            f = helper.create_variable_for_type_inference(x.dtype)
            helper.append_op(type="split_lod_tensor",
                             inputs={"X": [x], "Mask": [self.cond]},
                             outputs={"OutTrue": [t], "OutFalse": [f]},
                             attrs={"level": 0})
            self.input_table[x.name] = (t, f)
        t, f = self.input_table[x.name]
        return t if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS else f

    def output(self, *outs):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("output() must be inside a branch block")
        branch = self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS
        self._branch_outputs[branch].extend(outs)

    def __call__(self):
        t_outs = self._branch_outputs[True]
        f_outs = self._branch_outputs[False]
        if len(t_outs) != len(f_outs):
            raise ValueError("true/false branches must output the same "
                             "number of variables")
        rlist = []
        for t, f in zip(t_outs, f_outs):
            o = self.helper.create_variable_for_type_inference(t.dtype)
            self.helper.append_op(
                type="merge_lod_tensor",
                inputs={"X": [self.cond], "Mask": [self.cond],
                        "InTrue": [t], "InFalse": [f]},
                outputs={"Out": [o]}, attrs={"level": 0})
            rlist.append(o)
        return rlist
