"""Control-flow layers (reference: python/paddle/fluid/layers/control_flow.py
— While:3739, cond, increment, array ops, comparison wrappers)."""
from __future__ import annotations

import numpy as np

from ..core import VarDesc
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper

__all__ = [
    "While", "Switch", "increment", "array_write", "create_array",
    "less_than", "less_equal", "greater_than", "greater_equal", "equal",
    "not_equal", "array_read", "array_length", "cond", "IfElse",
    "StaticRNN", "Print", "Assert", "is_empty", "case", "switch_case",
    "while_loop", "DynamicRNN", "reorder_lod_tensor_by_rank",
]


def _cmp(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference(VarDesc.VarType.BOOL)
        cond.stop_gradient = True
        cond.shape = x.shape
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, force_cpu=None, cond=None):
    return _cmp("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _cmp("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _cmp("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _cmp("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _cmp("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _cmp("not_equal", x, y, cond)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
        out.shape = x.shape
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def create_array(dtype):
    helper = LayerHelper("array")
    return helper.main_program.current_block().create_var(
        name="{}.out".format(helper.name),
        type=VarDesc.VarType.LOD_TENSOR_ARRAY, dtype=dtype)


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(type="write_to_array",
                     inputs={"X": [x], "I": [i]}, outputs={"Out": [array]})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(type="read_from_array",
                     inputs={"X": [array], "I": [i]}, outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference(VarDesc.VarType.INT64)
    out.stop_gradient = True
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out


class While:
    """while loop over a sub-block (reference control_flow.py While)."""

    def __init__(self, cond, is_test=False, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.is_test = is_test

    class _BlockGuard:
        def __init__(self, while_obj):
            self.w = while_obj

        def __enter__(self):
            self.w._main = default_main_program()
            self.w._block = self.w._main._create_block()
            return self.w._block

        def __exit__(self, exc_type, exc_val, exc_tb):
            if exc_type is not None:
                return False
            main = self.w._main
            sub_block = main.current_block()
            main._rollback()
            parent = main.current_block()
            x_names = set()
            inner_outputs = {self.w.cond_var.name}
            for op in sub_block.ops:
                for name in op.input_arg_names:
                    if name not in inner_outputs:
                        x_names.add(name)
                inner_outputs.update(op.output_arg_names)
            out_vars = [n for n in inner_outputs
                        if parent.has_var_recursive(n)]
            step_scope = parent.create_var(
                type=VarDesc.VarType.STEP_SCOPES,
                name=self.w.helper.name + ".step_scopes")
            parent.append_op(
                type="while",
                inputs={"X": sorted(x_names), "Condition": [self.w.cond_var]},
                outputs={"Out": sorted(out_vars),
                         "StepScopes": [step_scope]},
                attrs={"sub_block": sub_block, "is_test": self.w.is_test})
            return True

    def block(self):
        return While._BlockGuard(self)


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """functional while (reference control_flow.py:3739 while_loop)."""
    pre_cond = cond(*loop_vars)
    w = While(pre_cond, is_test, name)
    with w.block():
        new_vars = body(*loop_vars)
        if not isinstance(new_vars, (list, tuple)):
            new_vars = [new_vars]
        from .tensor import assign
        for old, new in zip(loop_vars, new_vars):
            assign(new, old)
        new_cond = cond(*loop_vars)
        assign(new_cond, pre_cond)
    return loop_vars


def cond(pred, true_fn=None, false_fn=None, name=None):
    """two-branch conditional via conditional_block + select (reference
    control_flow.py cond)."""
    helper = LayerHelper("cond", name=name)
    main = default_main_program()
    from .tensor import cast, fill_constant
    from .nn import logical_not

    def _run_branch(fn, cond_var):
        block = main._create_block()
        out = fn() if fn is not None else None
        sub = main.current_block()
        main._rollback()
        parent = main.current_block()
        inner_out = set()
        x_names = set()
        for op in sub.ops:
            for n in op.input_arg_names:
                if n not in inner_out:
                    x_names.add(n)
            inner_out.update(op.output_arg_names)
        scope_var = parent.create_var(
            type=VarDesc.VarType.STEP_SCOPES,
            name=helper.name + ".branch_scope")
        parent.append_op(
            type="conditional_block",
            inputs={"Cond": [cond_var], "Input": sorted(x_names)},
            outputs={"Out": sorted(inner_out), "Scope": [scope_var]},
            attrs={"sub_block": sub, "is_scalar_condition": True})
        return out

    true_out = _run_branch(true_fn, pred)
    not_pred = logical_not(pred)
    false_out = _run_branch(false_fn, not_pred)
    if true_out is None and false_out is None:
        return None

    def _select(t, f):
        mask = cast(pred, VarDesc.VarType.INT32)
        o = helper.create_variable_for_type_inference(t.dtype)
        o.shape = t.shape
        helper.append_op(type="select_input",
                         inputs={"X": [f, t], "Mask": [mask]},
                         outputs={"Out": [o]})
        return o

    if isinstance(true_out, (list, tuple)):
        return [_select(t, f) for t, f in zip(true_out, false_out)]
    return _select(true_out, false_out)


def case(pred_fn_pairs, default=None, name=None):
    """reference control_flow.py case — chained cond."""
    pred, fn = pred_fn_pairs[0]
    if len(pred_fn_pairs) == 1:
        return cond(pred, fn, default, name)
    return cond(pred, fn, lambda: case(pred_fn_pairs[1:], default), name)


def switch_case(branch_index, branch_fns, default=None, name=None):
    from .tensor import fill_constant
    pairs = []
    for idx, fn in (branch_fns.items() if isinstance(branch_fns, dict)
                    else enumerate(branch_fns)):
        c = fill_constant([1], branch_index.dtype, idx)
        pairs.append((equal(branch_index, c), fn))
    return case(pairs, default, name)


class Switch:
    """reference control_flow.py Switch — used by lr schedulers."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.pre_not_conditions = []

    class _CaseGuard:
        def __init__(self, switch, cond_var):
            self.switch = switch
            self.cond_var = cond_var
            self.main = None

        def __enter__(self):
            from .nn import logical_and, logical_not
            self.main = default_main_program()
            s = self.switch
            if self.cond_var is not None:
                c = self.cond_var
                for nc in s.pre_not_conditions:
                    c = logical_and(c, nc)
                s.pre_not_conditions.append(logical_not(self.cond_var))
            else:
                c = None
                for i, nc in enumerate(s.pre_not_conditions):
                    c = nc if c is None else logical_and(c, nc)
            self.run_cond = c
            self.block = self.main._create_block()
            return self.block

        def __exit__(self, exc_type, exc_val, exc_tb):
            if exc_type is not None:
                return False
            main = self.main
            sub = main.current_block()
            main._rollback()
            parent = main.current_block()
            inner_out = set()
            x_names = set()
            for op in sub.ops:
                for n in op.input_arg_names:
                    if n not in inner_out:
                        x_names.add(n)
                inner_out.update(op.output_arg_names)
            scope_var = parent.create_var(
                type=VarDesc.VarType.STEP_SCOPES,
                name=self.switch.helper.name + ".case_scope")
            parent.append_op(
                type="conditional_block",
                inputs={"Cond": [self.run_cond], "Input": sorted(x_names)},
                outputs={"Out": sorted(inner_out), "Scope": [scope_var]},
                attrs={"sub_block": sub, "is_scalar_condition": True})
            return True

    def case(self, condition):
        return Switch._CaseGuard(self, condition)

    def default(self):
        return Switch._CaseGuard(self, None)

    def __enter__(self):
        return self

    def __exit__(self, *args):
        return False


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"first_n": first_n, "message": message or "",
                            "summarize": summarize,
                            "print_tensor_name": print_tensor_name,
                            "print_tensor_type": print_tensor_type,
                            "print_tensor_shape": print_tensor_shape,
                            "print_tensor_lod": print_tensor_lod,
                            "print_phase": print_phase.upper()})
    return out


def Assert(cond, data=None, summarize=20, name=None):
    helper = LayerHelper("assert", name=name)
    helper.append_op(type="assert",
                     inputs={"Cond": [cond],
                             "Data": list(data) if data else []},
                     outputs={}, attrs={"summarize": summarize})


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference(VarDesc.VarType.BOOL)
        cond.stop_gradient = True
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [cond]})
    return cond


class StaticRNN:
    def __init__(self, name=None):
        raise NotImplementedError("StaticRNN: use layers.rnn / lax.scan path")


class DynamicRNN:
    def __init__(self, name=None):
        raise NotImplementedError("DynamicRNN: use layers.rnn / lax.scan path")


class IfElse:
    def __init__(self, cond, name=None):
        raise NotImplementedError("IfElse: use layers.cond")


def reorder_lod_tensor_by_rank(x, rank_table):
    raise NotImplementedError("reorder_lod_tensor_by_rank: pending LoD batch")
