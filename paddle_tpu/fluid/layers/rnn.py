"""RNN cells and decoding (reference: python/paddle/fluid/layers/rnn.py —
RNNCell/GRUCell/LSTMCell, rnn(), dynamic_decode, BeamSearchDecoder).
TPU design: static-length scan (padded) is the fast path; rnn() builds the
unrolled/scan graph. Round-1 ships cells + static rnn; dynamic_decode and
beam search land with the seq2seq batch."""
from __future__ import annotations

__all__ = [
    "RNNCell", "GRUCell", "LSTMCell", "rnn", "Decoder", "BeamSearchDecoder",
    "dynamic_decode", "dynamic_lstm", "dynamic_lstmp", "dynamic_gru",
    "gru_unit", "lstm_unit", "lstm", "beam_search", "beam_search_decode",
]

from .. import layers as _L  # noqa — resolved lazily below
from ..layer_helper import LayerHelper


class RNNCell:
    def call(self, inputs, states, **kwargs):
        raise NotImplementedError

    def __call__(self, inputs, states, **kwargs):
        return self.call(inputs, states, **kwargs)

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        from .nn import fill_constant_batch_size_like
        shape = shape or self.state_shape
        if isinstance(shape[0], (list, tuple)):
            return [fill_constant_batch_size_like(
                batch_ref, [-1] + list(s), dtype, init_value) for s in shape]
        return fill_constant_batch_size_like(
            batch_ref, [-1] + list(shape), dtype, init_value)

    @property
    def state_shape(self):
        raise NotImplementedError


class GRUCell(RNNCell):
    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, dtype="float32",
                 name="GRUCell"):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._dtype = dtype
        self._name = name

    def call(self, inputs, states):
        from .nn import fc, elementwise_add, elementwise_mul, split
        from . import ops
        h = states
        gates = fc([inputs, h], 3 * self.hidden_size,
                   param_attr=self._param_attr, bias_attr=self._bias_attr)
        r, z, c = split(gates, 3, dim=-1)
        r, z = ops.sigmoid(r), ops.sigmoid(z)
        c = ops.tanh(c)
        new_h = z * h + (1.0 - z) * c
        return new_h, new_h

    @property
    def state_shape(self):
        return [self.hidden_size]


class LSTMCell(RNNCell):
    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, forget_bias=1.0,
                 dtype="float32", name="LSTMCell"):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._forget_bias = forget_bias
        self._dtype = dtype

    def call(self, inputs, states):
        from .nn import fc, split
        from . import ops
        h, c = states
        gates = fc([inputs, h], 4 * self.hidden_size,
                   param_attr=self._param_attr, bias_attr=self._bias_attr)
        i, f, o, j = split(gates, 4, dim=-1)
        i, f, o = ops.sigmoid(i), ops.sigmoid(f + self._forget_bias), ops.sigmoid(o)
        j = ops.tanh(j)
        new_c = c * f + i * j
        new_h = ops.tanh(new_c) * o
        return new_h, [new_h, new_c]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Static unrolled RNN over padded input [B, T, D] (or [T, B, D] when
    time_major). XLA unrolls into a fused loop; for long T prefer the scan
    path (models/ use lax.scan via dygraph)."""
    from .nn import transpose, stack, unstack
    from .tensor import concat
    if initial_states is None:
        initial_states = cell.get_initial_states(inputs)
    if not time_major:
        inputs_t = transpose(inputs, [1, 0] + list(range(2, len(inputs.shape))))
    else:
        inputs_t = inputs
    steps = unstack(inputs_t, axis=0)
    if is_reverse:
        steps = steps[::-1]
    states = initial_states
    outs = []
    for x_t in steps:
        o, states = cell(x_t, states, **kwargs)
        outs.append(o)
    if is_reverse:
        outs = outs[::-1]
    outputs = stack(outs, axis=0)
    if not time_major:
        outputs = transpose(outputs,
                            [1, 0] + list(range(2, len(outputs.shape))))
    return outputs, states


class Decoder:
    pass


class BeamSearchDecoder(Decoder):
    def __init__(self, *a, **k):
        raise NotImplementedError("BeamSearchDecoder: seq2seq batch pending")


def dynamic_decode(*a, **k):
    raise NotImplementedError("dynamic_decode: seq2seq batch pending")


def _nyi(name):
    def fn(*a, **k):
        raise NotImplementedError(f"{name}: LoD RNN pending; use rnn()/cells")
    fn.__name__ = name
    return fn


dynamic_lstm = _nyi("dynamic_lstm")
dynamic_lstmp = _nyi("dynamic_lstmp")
dynamic_gru = _nyi("dynamic_gru")
gru_unit = _nyi("gru_unit")
lstm_unit = _nyi("lstm_unit")
lstm = _nyi("lstm")
beam_search = _nyi("beam_search")
beam_search_decode = _nyi("beam_search_decode")
