"""RNN cells, recurrences and decoding (reference:
python/paddle/fluid/layers/rnn.py — RNNCell:33, GRUCell, LSTMCell, rnn(),
dynamic_decode:865, BeamSearchDecoder:224; layers/nn.py dynamic_lstm:466,
dynamic_lstmp:638, dynamic_gru:837, gru_unit:980, lstm:1040 (cudnn path)).

TPU design: LoD recurrences (dynamic_lstm/gru) lower to ONE masked
lax.scan over a LoD-padded batch (see ops/rnn_ops.py); decode runs a
static-trip-count unrolled loop with finished-masking (XLA-friendly, one
jit) and backtracks with gather_tree, instead of the reference's
While+LoD beam_search path — though that host path exists too
(layers.beam_search/beam_search_decode)."""
from __future__ import annotations

__all__ = [
    "RNNCell", "GRUCell", "LSTMCell", "rnn", "Decoder", "BeamSearchDecoder",
    "dynamic_decode", "DecodeHelper", "TrainingHelper",
    "GreedyEmbeddingHelper", "SampleEmbeddingHelper", "BasicDecoder",
    "dynamic_lstm", "dynamic_lstmp", "dynamic_gru",
    "gru_unit", "lstm_unit", "lstm", "beam_search", "beam_search_decode",
    "gather_tree",
]

from .. import unique_name
from ..core import VarDesc
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr


def _fixed_attr(attr, fallback_name):
    """Pin a param name so repeated cell calls (unrolled steps) share ONE
    parameter — create_parameter is idempotent per name."""
    if isinstance(attr, ParamAttr) and attr.name:
        return attr
    return ParamAttr(name=unique_name.generate(fallback_name))


def _cell_weight_attrs(attr, fallback_base):
    """TWO pinned names — input- and hidden-projection — for the cell's
    two-input fc. One shared name would tie Wx to Wh (round-4 fix: the
    name-dropping copy the helper used to make instead created a FRESH
    hidden weight per unrolled step, so the recurrence never shared
    weights across time). A user list of attrs passes through; a single
    user attr keeps all its fields (initializer, trainable, ...) in both
    derived copies — only the names are suffixed."""
    from ..layer_helper import copy_attr
    if isinstance(attr, (list, tuple)):
        return list(attr)
    if isinstance(attr, ParamAttr):
        base = attr.name or unique_name.generate(fallback_base)
        ax, ah = copy_attr(attr), copy_attr(attr)
        ax.name, ah.name = base + "_x", base + "_h"
        return [ax, ah]
    base = unique_name.generate(fallback_base)
    return [ParamAttr(name=base + "_x"), ParamAttr(name=base + "_h")]


class RNNCell:
    def call(self, inputs, states, **kwargs):
        raise NotImplementedError

    def __call__(self, inputs, states, **kwargs):
        return self.call(inputs, states, **kwargs)

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        from .tensor import fill_constant_batch_size_like
        shape = shape or self.state_shape
        if isinstance(shape[0], (list, tuple)):
            return [fill_constant_batch_size_like(
                batch_ref, [-1] + list(s), dtype, init_value) for s in shape]
        return fill_constant_batch_size_like(
            batch_ref, [-1] + list(shape), dtype, init_value)

    @property
    def state_shape(self):
        raise NotImplementedError


class GRUCell(RNNCell):
    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, dtype="float32",
                 name="GRUCell"):
        self.hidden_size = hidden_size
        self._param_attr = _cell_weight_attrs(param_attr, name + "_w")
        self._bias_attr = (bias_attr if bias_attr is False
                           else _fixed_attr(bias_attr, name + "_b"))
        self._dtype = dtype
        self._name = name

    def call(self, inputs, states):
        from .nn import fc, split
        from . import ops
        h = states
        gates = fc([inputs, h], 3 * self.hidden_size,
                   param_attr=self._param_attr, bias_attr=self._bias_attr)
        r, z, c = split(gates, 3, dim=-1)
        r, z = ops.sigmoid(r), ops.sigmoid(z)
        c = ops.tanh(c)
        new_h = z * h + (1.0 - z) * c
        return new_h, new_h

    @property
    def state_shape(self):
        return [self.hidden_size]


class LSTMCell(RNNCell):
    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, forget_bias=1.0,
                 dtype="float32", name="LSTMCell"):
        self.hidden_size = hidden_size
        self._param_attr = _cell_weight_attrs(param_attr, name + "_w")
        self._bias_attr = (bias_attr if bias_attr is False
                           else _fixed_attr(bias_attr, name + "_b"))
        self._forget_bias = forget_bias
        self._dtype = dtype

    def call(self, inputs, states):
        from .nn import fc, split
        from . import ops
        h, c = states
        gates = fc([inputs, h], 4 * self.hidden_size,
                   param_attr=self._param_attr, bias_attr=self._bias_attr)
        i, f, o, j = split(gates, 4, dim=-1)
        i, f, o = ops.sigmoid(i), ops.sigmoid(f + self._forget_bias), ops.sigmoid(o)
        j = ops.tanh(j)
        new_c = c * f + i * j
        new_h = ops.tanh(new_c) * o
        return new_h, [new_h, new_c]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Static unrolled RNN over padded input [B, T, D] (or [T, B, D] when
    time_major). XLA fuses the unrolled steps; LoD inputs should use
    dynamic_lstm/dynamic_gru (single scan)."""
    from .nn import transpose, stack, unstack
    if initial_states is None:
        initial_states = cell.get_initial_states(inputs)
    if not time_major:
        inputs_t = transpose(inputs, [1, 0] + list(range(2, len(inputs.shape))))
    else:
        inputs_t = inputs
    steps = unstack(inputs_t, axis=0)
    if is_reverse:
        steps = steps[::-1]
    states = initial_states
    outs = []
    for x_t in steps:
        o, states = cell(x_t, states, **kwargs)
        outs.append(o)
    if is_reverse:
        outs = outs[::-1]
    outputs = stack(outs, axis=0)
    if not time_major:
        outputs = transpose(outputs,
                            [1, 0] + list(range(2, len(outputs.shape))))
    return outputs, states


# --------------------------------------------------------------------------
# LoD recurrent layers
# --------------------------------------------------------------------------
def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """input: packed LoD [T, 4H] (pre-projected); size = 4*hidden."""
    helper = LayerHelper("dynamic_lstm", **locals())
    H = size // 4
    weight = helper.create_parameter(attr=param_attr, shape=[H, 4 * H],
                                     dtype=dtype)
    bias_size = [1, 7 * H] if use_peepholes else [1, 4 * H]
    bias = helper.create_parameter(attr=bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    hidden.shape = (-1, H)
    cell.shape = (-1, H)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(type="dynamic_lstm", inputs=inputs,
                     outputs={"Hidden": [hidden], "Cell": [cell]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, h_0=None, c_0=None,
                  cell_clip=None, proj_clip=None):
    helper = LayerHelper("dynamic_lstmp", **locals())
    H = size // 4
    P = proj_size
    weight = helper.create_parameter(attr=param_attr, shape=[P, 4 * H],
                                     dtype=dtype)
    proj_weight = helper.create_parameter(attr=None, shape=[H, P], dtype=dtype)
    bias_size = [1, 7 * H] if use_peepholes else [1, 4 * H]
    bias = helper.create_parameter(attr=bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    projection = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    projection.shape = (-1, P)
    cell.shape = (-1, H)
    inputs = {"Input": [input], "Weight": [weight],
              "ProjWeight": [proj_weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(type="dynamic_lstmp", inputs=inputs,
                     outputs={"Projection": [projection], "Cell": [cell]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation,
                            "proj_activation": proj_activation})
    return projection, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                name=None):
    """input: packed LoD [T, 3H]; size = hidden."""
    helper = LayerHelper("dynamic_gru", **locals())
    dtype = helper.input_dtype()
    weight = helper.create_parameter(attr=param_attr, shape=[size, 3 * size],
                                     dtype=dtype)
    bias = helper.create_parameter(attr=bias_attr, shape=[1, 3 * size],
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    hidden.shape = (-1, size)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(type="dynamic_gru", inputs=inputs,
                     outputs={"Hidden": [hidden]},
                     attrs={"is_reverse": is_reverse,
                            "origin_mode": origin_mode,
                            "gate_activation": gate_activation,
                            "activation": candidate_activation})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """Single GRU step; size = 3*hidden."""
    helper = LayerHelper("gru_unit", **locals())
    dtype = helper.input_dtype()
    H = size // 3
    weight = helper.create_parameter(attr=param_attr, shape=[H, 3 * H],
                                     dtype=dtype)
    bias = helper.create_parameter(attr=bias_attr, shape=[1, 3 * H],
                                   dtype=dtype, is_bias=True)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden_pre = helper.create_variable_for_type_inference(dtype)
    updated_hidden = helper.create_variable_for_type_inference(dtype)
    updated_hidden.shape = (-1, H)
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden],
                "Weight": [weight], "Bias": [bias]},
        outputs={"Gate": [gate], "ResetHiddenPrev": [reset_hidden_pre],
                 "Hidden": [updated_hidden]},
        attrs={"activation": activation, "gate_activation": gate_activation,
               "origin_mode": origin_mode})
    return updated_hidden, reset_hidden_pre, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step over dense [N, D] input: fc([x, h]) + lstm_unit op."""
    from .nn import fc
    helper = LayerHelper("lstm_unit", **locals())
    H = hidden_t_prev.shape[-1]
    gates = fc([x_t, hidden_t_prev], 4 * H, param_attr=param_attr,
               bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    c.shape = tuple(cell_t_prev.shape)
    h.shape = tuple(cell_t_prev.shape)
    helper.append_op(type="lstm_unit",
                     inputs={"X": [gates], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": forget_bias})
    return h, c


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Dense multi-layer (bi)LSTM over [B, T, D]. Weight is one flat param
    packing per layer/direction [Wx, Wh, b] in order (ops/rnn_ops.py)."""
    helper = LayerHelper("lstm", **locals())
    dtype = helper.input_dtype()
    D = input.shape[-1]
    H, L = hidden_size, num_layers
    dirs = 2 if is_bidirec else 1
    total = 0
    in_dim = D
    for _layer in range(L):
        total += dirs * (in_dim * 4 * H + H * 4 * H + 4 * H)
        in_dim = H * dirs
    w = helper.create_parameter(attr=None, shape=[total], dtype=dtype,
                                default_initializer=default_initializer)
    out_v = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    out_v.shape = tuple(input.shape[:-1]) + (H * dirs,)
    helper.append_op(
        type="lstm",
        inputs={"Input": [input], "W": [w], "InitH": [init_h],
                "InitC": [init_c]},
        outputs={"Out": [out_v], "LastH": [last_h], "LastC": [last_c]},
        attrs={"max_len": max_len, "hidden_size": H, "num_layers": L,
               "is_bidirec": is_bidirec, "dropout_prob": dropout_prob,
               "is_test": is_test, "input_size": D,
               "seed": seed if seed and seed > 0 else 0})
    return out_v, last_h, last_c


# --------------------------------------------------------------------------
# beam search (LoD host path)
# --------------------------------------------------------------------------
def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    helper = LayerHelper("beam_search", **locals())
    selected_ids = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT64)
    selected_scores = helper.create_variable_for_type_inference(
        VarDesc.VarType.FP32)
    parent_idx = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT64)
    inputs = {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
              "scores": [scores]}
    if ids is not None:
        inputs["ids"] = [ids]
    helper.append_op(
        type="beam_search", inputs=inputs,
        outputs={"selected_ids": [selected_ids],
                 "selected_scores": [selected_scores],
                 "parent_idx": [parent_idx]},
        attrs={"level": level, "beam_size": beam_size, "end_id": end_id,
               "is_accumulated": is_accumulated})
    if return_parent_idx:
        return selected_ids, selected_scores, parent_idx
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, beam_size, end_id, name=None):
    helper = LayerHelper("beam_search_decode", **locals())
    sentence_ids = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT64)
    sentence_scores = helper.create_variable_for_type_inference(
        VarDesc.VarType.FP32)
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Scores": [scores]},
        outputs={"SentenceIds": [sentence_ids],
                 "SentenceScores": [sentence_scores]},
        attrs={"beam_size": beam_size, "end_id": end_id})
    return sentence_ids, sentence_scores


def gather_tree(ids, parents):
    helper = LayerHelper("gather_tree")
    out = helper.create_variable_for_type_inference(ids.dtype)
    helper.append_op(type="gather_tree",
                     inputs={"Ids": [ids], "Parents": [parents]},
                     outputs={"Out": [out]})
    return out


# --------------------------------------------------------------------------
# decode helpers (reference rnn.py DecodeHelper:1375, TrainingHelper:1444,
# GreedyEmbeddingHelper:1597, SampleEmbeddingHelper:1728, BasicDecoder:1829)
#
# TPU inversion: dynamic_decode runs a STATIC trip-count unrolled loop,
# so `time` reaches the helpers as a Python int (compile-time constant)
# instead of an int64 Variable — slices are static and XLA-friendly.
# --------------------------------------------------------------------------
class DecodeHelper:
    """Sampling + next-step-input strategy plugged into BasicDecoder."""

    def initialize(self):
        """-> (initial_inputs, initial_finished)."""
        raise NotImplementedError

    def sample(self, time, outputs, states):
        """-> int64 sample ids for the current step."""
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        """-> (finished, next_inputs, next_states)."""
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher-forcing helper: step inputs are slices of the full target
    sequence; sample() is argmax (ids mostly unused)."""

    def __init__(self, inputs, sequence_length, time_major=False):
        self.inputs = inputs
        self.sequence_length = sequence_length
        self.time_major = time_major

    def _slice(self, t):
        import paddle_tpu.fluid.layers as L
        axis = 0 if self.time_major else 1
        T = self.inputs.shape[axis]
        t = min(t, T - 1)  # clamp instead of the reference's pad-by-one
        return L.squeeze(L.slice(self.inputs, axes=[axis], starts=[t],
                                 ends=[t + 1]), [axis])

    def initialize(self):
        import paddle_tpu.fluid.layers as L
        zero = L.fill_constant([1], self.sequence_length.dtype, 0)
        return self._slice(0), L.equal(self.sequence_length, zero)

    def sample(self, time, outputs, states):
        import paddle_tpu.fluid.layers as L
        return L.cast(L.argmax(outputs, axis=-1), "int64")

    def next_inputs(self, time, outputs, states, sample_ids):
        import paddle_tpu.fluid.layers as L
        nxt = L.fill_constant([1], self.sequence_length.dtype,
                              int(time) + 1)
        finished = L.less_equal(self.sequence_length, nxt)
        return finished, self._slice(int(time) + 1), states


class GreedyEmbeddingHelper(DecodeHelper):
    """Inference helper: argmax ids fed back through an embedding."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        import paddle_tpu.fluid.layers as L
        self.embedding_fn = embedding_fn
        self.start_tokens = start_tokens
        self.end_token = L.fill_constant([1], "int64", end_token)

    def initialize(self):
        import paddle_tpu.fluid.layers as L
        finished = L.cast(L.zeros_like(self.start_tokens), "bool")
        return self.embedding_fn(self.start_tokens), finished

    def sample(self, time, outputs, states):
        import paddle_tpu.fluid.layers as L
        return L.cast(L.argmax(outputs, axis=-1), "int64")

    def next_inputs(self, time, outputs, states, sample_ids):
        import paddle_tpu.fluid.layers as L
        finished = L.equal(sample_ids, self.end_token)
        return finished, self.embedding_fn(sample_ids), states


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Like GreedyEmbeddingHelper but draws from softmax(logits/T)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.softmax_temperature = softmax_temperature
        self.seed = seed

    def sample(self, time, outputs, states):
        import paddle_tpu.fluid.layers as L
        logits = outputs
        if self.softmax_temperature is not None:
            logits = L.scale(logits,
                             scale=1.0 / float(self.softmax_temperature))
        probs = L.softmax(logits)
        probs.stop_gradient = True
        return L.sampling_id(probs, seed=self.seed or 0)


# --------------------------------------------------------------------------
# tensor-based decode
# --------------------------------------------------------------------------
class Decoder:
    """Base decoder interface (reference rnn.py Decoder:132)."""


class BeamSearchDecoder(Decoder):
    """Dense beam-search decoder (reference rnn.py BeamSearchDecoder:224).

    embedding_fn: ids [N, 1] -> embeddings; output_fn: cell output ->
    vocab logits. Used with dynamic_decode below."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


class BasicDecoder(Decoder):
    """Cell + DecodeHelper assembly (reference rnn.py BasicDecoder:1829):
    step = cell.call → output_fn → helper.sample → helper.next_inputs."""
    import collections as _collections
    OutputWrapper = _collections.namedtuple("OutputWrapper",
                                            ("cell_outputs", "sample_ids"))

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        initial_inputs, initial_finished = self.helper.initialize()
        return initial_inputs, initial_cell_states, initial_finished

    def step(self, time, inputs, states, **kwargs):
        cell_outputs, cell_states = self.cell(inputs, states, **kwargs)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        sample_ids = self.helper.sample(time=time, outputs=cell_outputs,
                                        states=cell_states)
        sample_ids.stop_gradient = True
        finished, next_inputs, next_states = self.helper.next_inputs(
            time=time, outputs=cell_outputs, states=cell_states,
            sample_ids=sample_ids)
        return (self.OutputWrapper(cell_outputs, sample_ids), next_states,
                next_inputs, finished)


def _dynamic_decode_generic(decoder, inits, max_step_num,
                            output_time_major, return_length=False,
                            **kwargs):
    """decoder.initialize/step protocol (BasicDecoder et al.) under the
    same static-trip-count inversion: `time` is a Python int, finished
    status latches via logical_or, outputs are stacked over time.
    Returns (outputs_structure, final_states) like the reference, plus
    the decode lengths when return_length (the step emitting the end
    token counts, later steps don't)."""
    import paddle_tpu.fluid.layers as L
    if max_step_num is None:
        max_step_num = 32
    inputs, states, finished = decoder.initialize(inits)
    steps = []
    lengths = None
    for t in range(int(max_step_num)):
        outputs, states, inputs, step_fin = decoder.step(
            t, inputs, states, **kwargs)
        alive = L.cast(L.logical_not(finished), "int64")
        lengths = alive if lengths is None \
            else L.elementwise_add(lengths, alive)
        finished = L.logical_or(finished, step_fin)
        steps.append(outputs)

    def _stack(field_vals):
        s = L.stack(list(field_vals), axis=0)          # [T, B, ...]
        if not output_time_major:
            s = L.transpose(s, [1, 0] + list(range(2, len(s.shape))))
        return s

    first = steps[0]
    if hasattr(first, "_fields"):  # namedtuple of per-step tensors
        final = type(first)(*[_stack([getattr(s, f) for s in steps])
                              for f in first._fields])
    else:
        final = _stack(steps)
    if return_length:
        return final, states, lengths
    return final, states


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, return_length=False, **kwargs):
    """Decode with a STATIC trip count (TPU inversion of the reference's
    While loop, rnn.py dynamic_decode:865). BeamSearchDecoder: every step
    extends all beams; finished beams are frozen by score masking;
    gather_tree backtracks at the end; returns (predicted_ids
    [B, T, beam], final_scores [B, beam]). Decoders exposing the
    initialize/step protocol (BasicDecoder) return
    (outputs_structure, final_states[, lengths when return_length])."""
    if not isinstance(decoder, BeamSearchDecoder) and \
            hasattr(decoder, "initialize") and hasattr(decoder, "step"):
        return _dynamic_decode_generic(decoder, inits, max_step_num,
                                       output_time_major, return_length,
                                       **kwargs)
    import paddle_tpu.fluid.layers as L
    from paddle_tpu.fluid.layers import (
        topk, reshape, expand, unsqueeze, squeeze, transpose, cast, gather,
        stack, elementwise_add, elementwise_mul, elementwise_sub,
        elementwise_mod, elementwise_floordiv, fill_constant_batch_size_like,
        one_hot, slice, cumsum, zeros_like, equal, fill_constant)
    nn = L
    if max_step_num is None:
        max_step_num = 32
    cell = decoder.cell
    beam = decoder.beam_size
    end = decoder.end_token

    states = inits
    if not isinstance(states, (list, tuple)):
        states = [states]

    def tile(x):
        h = x.shape[-1]
        t = unsqueeze(x, [1])                     # [B, 1, H]
        t = expand(t, [1, beam, 1])               # [B, beam, H]
        return reshape(t, [-1, h])                # [B*beam, H]

    flat_states = [tile(s) for s in states]
    ref = flat_states[0]

    step_ids, step_parents = [], []
    token, scores = None, None
    for t in range(max_step_num):
        if t == 0:
            inp_tok = fill_constant_batch_size_like(
                ref, [-1, 1], "int64", decoder.start_token)
        else:
            inp_tok = reshape(token, [-1, 1])
        emb = decoder.embedding_fn(inp_tok)
        emb = reshape(emb, [-1, emb.shape[-1]])
        packed = flat_states if len(flat_states) > 1 else flat_states[0]
        cell_out, new_states = cell(emb, packed, **kwargs)
        flat_states = (list(new_states)
                       if isinstance(new_states, (list, tuple))
                       else [new_states])
        logits = (decoder.output_fn(cell_out) if decoder.output_fn
                  else cell_out)
        V = logits.shape[-1]
        logp = nn.log(nn.softmax(logits))          # [B*beam, V]
        logp3 = reshape(logp, [-1, beam, V])
        if t == 0:
            first = squeeze(slice(logp3, axes=[1], starts=[0], ends=[1]), [1])
            scores, token = topk(first, beam)      # [B, beam]
            parent = zeros_like(token)
        else:
            fin = cast(equal(token,
                             fill_constant([1], "int64", end)), "float32")
            fin3 = unsqueeze(fin, [2])             # [B, beam, 1]
            end_row = one_hot(
                reshape(fill_constant([1], "int64", end), [1, 1]), V)
            end_mask = elementwise_sub(
                elementwise_mul(end_row, fill_constant([1], "float32", 1e9)),
                fill_constant([1], "float32", 1e9))  # 0 at end, -1e9 else
            step_scores = elementwise_add(
                elementwise_mul(logp3, 1.0 - fin3),
                elementwise_mul(
                    expand(reshape(end_mask, [1, 1, V]),
                           [1, beam, 1]), fin3))
            total = elementwise_add(unsqueeze(scores, [2]), step_scores)
            flat = reshape(total, [-1, beam * V])
            scores, flat_idx = topk(flat, beam)    # [B, beam]
            vconst = fill_constant([1], "int64", V)
            parent = elementwise_floordiv(flat_idx, vconst)
            token = elementwise_mod(flat_idx, vconst)
            # reorder states to follow the selected parents:
            # abs_row = batch_idx * beam + parent
            ones = fill_constant_batch_size_like(scores, [-1, beam],
                                                 "int64", 1)
            batch_pos = elementwise_sub(cumsum(ones, axis=0), ones)
            abs_idx = reshape(
                elementwise_add(
                    elementwise_mul(batch_pos,
                                    fill_constant([1], "int64", beam)),
                    parent), [-1])
            flat_states = [gather(s, abs_idx) for s in flat_states]
        step_ids.append(token)
        step_parents.append(parent)
    ids_t = stack(step_ids, axis=0)                # [T, B, beam]
    parents_t = stack(step_parents, axis=0)
    predicted = gather_tree(ids_t, parents_t)
    if not output_time_major:
        predicted = transpose(predicted, [1, 0, 2])
    return predicted, scores
