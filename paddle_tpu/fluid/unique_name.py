"""Unique name generator (reference: python/paddle/fluid/unique_name.py
behaviour: per-key counters, ``guard`` to swap generators, ``switch``)."""
from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "switch", "guard"]


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.ids = defaultdict(int)
        self.prefix = prefix

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return self.prefix + "_".join([key, str(tmp)])


generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return generator(key)


# dygraph parameter names must stay unique across programs; the reference
# keeps a separate generator for that (unique_name.py generate_with_ignorable_key)
dygraph_parameter_name_generator = UniqueNameGenerator()


def generate_with_ignorable_key(key: str) -> str:
    return dygraph_parameter_name_generator(key)


def switch(new_generator=None):
    global generator
    old = generator
    generator = new_generator if new_generator is not None else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
