"""Communicator — async grad merge/send threads for PS training
(reference: python/paddle/fluid/communicator.py:27,91 wrapping C++
operators/distributed/communicator.h — AsyncCommunicator:237 merge queues,
HalfAsyncCommunicator:299, GeoCommunicator:383).

TPU framing: the pserver applies updates on arrival
(ops/distributed_ops.py listen_and_serv async loop), so correctness never
needs client-side queues — but the reference's merge behavior matters for
RPC load: with a running Communicator, async-mode send ops enqueue grads
here instead of issuing one RPC each; per-var merge threads sum up to
``max_merge_var_num`` pending grads and ship one merged send (the
AsyncCommunicator contract). SYNC mode needs no communicator at all."""
from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from . import core

__all__ = ["Communicator", "LargeScaleKV", "RoundPipeline",
           "round_pipeline", "active_round_pipeline",
           "drain_async_rounds", "reset_round_pipeline",
           "DGCCompressor", "dgc_compressor", "dgc_enabled",
           "reset_dgc", "topk_sparsify", "geo_round_pipeline",
           "active_geo_pipeline", "reset_geo_pipeline"]

_LOG = logging.getLogger("paddle_tpu.ps")


# ---------------------------------------------------------------------------
# DGC — deep gradient compression (docs/PS_DATA_PLANE.md "Compression";
# reference WITH_DGC, paddle/fluid/operators/dgc_op + DGCMomentumOptimizer;
# Lin et al., "Deep Gradient Compression", ICLR 2018). Dense grads on the
# sync send / ps_round paths sparsify to their top-k elements before the
# wire; the unsent mass stays in a LOCAL error-feedback accumulator and
# ships in later pushes, so the sum of everything sent plus the residual
# always equals the true accumulated gradient (the convergence contract —
# tested in tests/test_ps_compression.py).
# ---------------------------------------------------------------------------
def dgc_enabled() -> bool:
    return bool(core.globals_["FLAGS_dgc"])


def topk_sparsify(flat: np.ndarray, sparsity: float):
    """Top-k-by-magnitude selection: keep ceil(n*(1-sparsity)) entries
    (at least 1). Returns (sorted int64 indices, their values) —
    sorted so the server-side scatter order is deterministic."""
    n = int(flat.size)
    k = max(1, int(round(n * (1.0 - float(sparsity)))))
    if k >= n:
        idx = np.arange(n, dtype=np.int64)
    else:
        idx = np.argpartition(np.abs(flat), n - k)[n - k:]
        idx = np.sort(idx).astype(np.int64)
    return idx, np.ascontiguousarray(flat[idx])


class DGCCompressor:
    """Per-trainer DGC state: for each grad name a momentum-corrected
    velocity ``u`` (u = m*u + g) and an error-feedback accumulator
    ``v`` (v += u). Each push selects the top-k of |v|, zeroes the
    selected entries of BOTH u and v (the paper's momentum factor
    masking), and ships (indices, values); everything unselected stays
    local and accumulates into later pushes. Warm-up ramps sparsity
    exponentially toward FLAGS_dgc_sparsity over the first
    FLAGS_dgc_warmup_steps pushes per grad."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state: Dict[str, dict] = {}
        self._stats = {"elements_total": 0, "elements_sent": 0,
                       "bytes_raw_total": 0, "bytes_sent_total": 0,
                       "pushes_total": 0, "dense_fallbacks_total": 0}

    @staticmethod
    def _sparsity_at(step: int) -> float:
        final = min(0.9999, max(0.0,
                    float(core.globals_["FLAGS_dgc_sparsity"])))
        warm = int(core.globals_["FLAGS_dgc_warmup_steps"])
        if warm > 0 and step < warm and final > 0:
            # exponential ramp (the paper's per-epoch 75%→99.9%
            # schedule, per-push): drop rate approaches `final` as
            # (1-final)^((step+1)/warm)
            return 1.0 - (1.0 - final) ** (float(step + 1) / warm)
        return final

    def compress(self, name: str, grad: np.ndarray):
        """Fold ``grad`` into the local accumulators and select this
        push's top-k. Returns (indices, values) over the FLAT grad, or
        None when the grad should ship dense (non-f32 or smaller than
        FLAGS_dgc_min_elements)."""
        g = np.asarray(grad)
        if g.dtype != np.float32 \
                or g.size < int(core.globals_["FLAGS_dgc_min_elements"]):
            return None
        m = float(core.globals_["FLAGS_dgc_momentum"])
        with self._lock:
            st = self._state.get(name)
            if st is None or st["u"].size != g.size:
                st = self._state[name] = {
                    "u": np.zeros(g.size, np.float32),
                    "v": np.zeros(g.size, np.float32), "step": 0}
            u, v = st["u"], st["v"]
            flat = g.reshape(-1)
            if m > 0.0:
                u *= np.float32(m)
                u += flat
            else:
                u[:] = flat
            v += u
            idx, vals = topk_sparsify(
                v, self._sparsity_at(st["step"]))
            st["step"] += 1
            v[idx] = 0.0
            u[idx] = 0.0  # momentum factor masking
            self._stats["elements_total"] += int(g.size)
            self._stats["elements_sent"] += int(idx.size)
            self._stats["bytes_raw_total"] += int(g.nbytes)
            self._stats["bytes_sent_total"] += int(idx.nbytes
                                                   + vals.nbytes)
            self._stats["pushes_total"] += 1
        return idx, vals

    def restore_dense(self, name: str, idx: np.ndarray,
                      vals: np.ndarray) -> np.ndarray:
        """Undo a compress() whose dgc_send met an old server ("no
        method"): put the selected mass back and return the FULL flat
        accumulator to ship dense instead — the residual clears, so
        nothing is lost or double-sent across the fallback."""
        with self._lock:
            st = self._state[name]
            v = st["v"]
            v[idx] += vals  # selected entries were zeroed above
            full = v.copy()
            v[:] = 0.0
            st["u"][:] = 0.0
            self._stats["dense_fallbacks_total"] += 1
        return full

    def note_external(self, total_elems: int, sent_elems: int,
                      raw_bytes: int, sent_bytes: int) -> None:
        """Fold an externally-compressed push (the geo-delta top-k
        lane keeps its error feedback in @GEO_OLD, not in u/v) into
        the same dgc_* counters so dgc_compression_ratio covers the
        whole compressed plane."""
        with self._lock:
            self._stats["elements_total"] += int(total_elems)
            self._stats["elements_sent"] += int(sent_elems)
            self._stats["bytes_raw_total"] += int(raw_bytes)
            self._stats["bytes_sent_total"] += int(sent_bytes)
            self._stats["pushes_total"] += 1

    def residual(self, name: str):
        """Copy of the error-feedback accumulator (tests/debugging)."""
        with self._lock:
            st = self._state.get(name)
            return None if st is None else st["v"].copy()

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
        out["compression_ratio"] = round(
            out["elements_total"] / max(1, out["elements_sent"]), 2)
        return out


_dgc: Optional[DGCCompressor] = None
_dgc_lock = threading.Lock()
_dgc_view = None


def dgc_compressor() -> DGCCompressor:
    """Process-global compressor (one trainer per process, like the
    round pipeline); registers the ``dgc`` metrics view — the
    ``dgc_compression_ratio`` gauge — on first use."""
    global _dgc, _dgc_view
    with _dgc_lock:
        if _dgc is None:
            _dgc = DGCCompressor()
            from . import telemetry
            _dgc_view = telemetry.REGISTRY.register_view(
                "dgc", _dgc.stats)
        return _dgc


def active_dgc_stats() -> dict:
    """Compression counters of the live compressor ({} when DGC never
    ran in this process) — the subprocess-evidence surface the WAN
    scenario and bench lanes collect."""
    d = _dgc
    return {} if d is None else d.stats()


def reset_dgc():
    global _dgc, _dgc_view
    with _dgc_lock:
        _dgc = None
        view, _dgc_view = _dgc_view, None
    if view is not None:
        from . import telemetry
        telemetry.REGISTRY.unregister_view(view)


class RoundPipeline:
    """The half-async round engine of the async overlap plane
    (docs/PS_DATA_PLANE.md "Async overlap"; reference
    HalfAsyncCommunicator, operators/distributed/communicator.h:299).

    A sync trainer's comm tail (push grads → send barrier → pull params
    → fetch barrier) is submitted here as ONE callable per round; a
    single FIFO worker thread runs rounds in submit order — the
    server's sync protocol needs exactly one send per trainer per round
    and in-order barrier arrivals, so rounds never overlap EACH OTHER
    on the wire, only the trainer's compute. The ps_rpc.AckWindow
    bounds how many submitted-but-unacked rounds may be in flight
    (FLAGS_async_staleness); a full pipe blocks ``submit`` — i.e. the
    step. Round callables return the round's pulled params (the
    double-buffer fill); ``take_fresh_pulls`` hands the NEWEST
    completed buffer to the main thread exactly once, which installs it
    into the scope at the next step boundary.

    Ordered non-round tasks (``submit_task``) ride the same FIFO — the
    async sparse-grad pushes of step i+1 must reach the server after
    round i's release and before round i+1's sends, exactly where the
    sync path would have put them."""

    def __init__(self, name: str = "ps-async-rounds"):
        from .ps_rpc import AckWindow
        self._name = name
        self._q: "queue.Queue" = queue.Queue()
        self._ack = AckWindow()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._running = True
        # newest completed pull buffer: (round_id, {param: ndarray});
        # _installed tracks what the main thread already consumed
        self._latest = (-1, None)
        self._installed = -1
        # queued-or-executing side tasks: the AckWindow only tracks
        # ROUNDS, but drain() must also cover a sparse push that was
        # dequeued and is still on the wire (otherwise a drain with no
        # round behind the push returns early and the push is lost to
        # a following server stop)
        self._tasks_cv = threading.Condition()
        self._tasks_pending = 0

    # ------------------------------------------------------------ submit
    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name=self._name, daemon=True)
                self._thread.start()

    def submit(self, fn, staleness: int, label: str = "round") -> int:
        """Submit one round; blocks while ``staleness`` rounds are in
        flight (the full-pipe backpressure) and re-raises any deferred
        background error typed on this (the main) thread."""
        from . import profiler as _profiler
        self._ensure_thread()
        if self._ack.inflight() >= max(1, int(staleness)) \
                and _profiler.is_profiling():
            with _profiler.RecordEvent(
                    f"{label}:stall[pipe_full]", cat="comm",
                    args={"inflight": self._ack.inflight()}):
                rid = self._ack.acquire_slot(staleness)
        else:
            rid = self._ack.acquire_slot(staleness)
        self._q.put(("round", rid, fn, label))
        return rid

    def submit_task(self, fn, label: str = "task") -> None:
        """FIFO side task (async sparse push): ordered with the rounds,
        outside the staleness accounting; errors surface at the next
        submit()/drain()."""
        self._ensure_thread()
        with self._tasks_cv:
            self._tasks_pending += 1
        self._q.put(("task", -1, fn, label))

    # -------------------------------------------------------------- loop
    def _loop(self):
        from . import profiler as _profiler
        while True:
            kind, rid, fn, label = self._q.get()
            if kind == "stop":
                return
            try:
                if _profiler.is_profiling():
                    with _profiler.RecordEvent(
                            f"{label}[{rid}]" if kind == "round"
                            else label, cat="comm"):
                        result = fn()
                else:
                    result = fn()
                if kind == "round" and isinstance(result, dict) \
                        and result:
                    with self._lock:
                        if rid > self._latest[0]:
                            self._latest = (rid, result)
                err = None
            except BaseException as e:  # noqa: BLE001 — deferred, typed
                err = e
                _LOG.warning("%s: background %s %s failed: %r",
                             self._name, kind, label, e)
            if kind == "round":
                self._ack.ack(err)
            else:
                if err is not None:
                    self._ack.record_error(err)
                with self._tasks_cv:
                    self._tasks_pending -= 1
                    self._tasks_cv.notify_all()

    # ------------------------------------------------------ double buffer
    def take_fresh_pulls(self):
        """The newest completed round's pulled params, or None when the
        main thread already installed them — the at-a-step-boundary
        half of the double-buffered dense pull."""
        with self._lock:
            rid, buf = self._latest
            if buf is None or rid <= self._installed:
                return None
            self._installed = rid
            return buf

    # -------------------------------------------------------------- drain
    def inflight(self) -> int:
        return self._ack.inflight()

    def stats(self) -> dict:
        """Round-pipeline counters (docs/OBSERVABILITY.md): submitted/
        acked/inflight rounds, pending side tasks, and the double-buffer
        install watermark — registered as the ``ps_round_pipeline``
        metrics view by ``round_pipeline()``."""
        submitted, acked = self._ack.counts()
        with self._tasks_cv:
            tasks_pending = self._tasks_pending
        with self._lock:
            latest, installed = self._latest[0], self._installed
        return {"rounds_submitted": submitted, "rounds_acked": acked,
                "rounds_inflight": submitted - acked,
                "tasks_pending": tasks_pending,
                "latest_pull_round": latest,
                "installed_pull_round": installed}

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for every submitted round (and queued task) to finish —
        FIFO, so the flush order is deterministic. Returns False on
        timeout. Deferred errors re-raise here."""
        end = None if timeout is None else time.time() + timeout
        while not self._q.empty():
            if end is not None and time.time() > end:
                return False
            time.sleep(0.005)
        if not self._ack.wait_all(
                None if end is None else max(0.0, end - time.time())):
            return False
        with self._tasks_cv:
            while self._tasks_pending > 0:
                wait = None if end is None else end - time.time()
                if wait is not None and wait <= 0:
                    return False
                self._tasks_cv.wait(wait if wait is None
                                    else min(wait, 1.0))
        return True

    def stop(self, timeout: Optional[float] = None):
        try:
            self.drain(timeout)
        except BaseException as e:  # noqa: BLE001 — teardown must finish
            _LOG.warning("%s: error surfaced during stop-drain: %r",
                         self._name, e)
        self._q.put(("stop", -1, None, ""))
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)


# process-global pipeline: the ps_round op kernels have no trainer
# context, and one trainer process runs one staleness pipe (mirrors the
# install_row_cache layering in ps_rpc)
_round_pipe: Optional[RoundPipeline] = None
_round_pipe_lock = threading.Lock()
_round_pipe_view = None


def round_pipeline() -> RoundPipeline:
    global _round_pipe, _round_pipe_view
    with _round_pipe_lock:
        if _round_pipe is None:
            _round_pipe = RoundPipeline()
            from . import telemetry
            _round_pipe_view = telemetry.REGISTRY.register_view(
                "ps_round_pipeline", _round_pipe.stats)
        return _round_pipe


def active_round_pipeline() -> Optional[RoundPipeline]:
    return _round_pipe


# geo-delta WAN lane (docs/PS_DATA_PLANE.md "Compression"): geo_sgd_send
# submits its delta-merge rounds here when FLAGS_async_staleness > 0 —
# a SEPARATE pipe from the sync ps_round one (a process never runs
# both, but the stats views must not conflate them and geo rounds have
# their own install protocol: a FIFO shift queue, not the newest-pull
# double buffer).
_geo_pipe: Optional[RoundPipeline] = None
_geo_pipe_view = None


def geo_round_pipeline() -> RoundPipeline:
    global _geo_pipe, _geo_pipe_view
    with _round_pipe_lock:
        if _geo_pipe is None:
            _geo_pipe = RoundPipeline(name="ps-geo-rounds")
            from . import telemetry
            _geo_pipe_view = telemetry.REGISTRY.register_view(
                "ps_geo_pipeline", _geo_pipe.stats)
        return _geo_pipe


def active_geo_pipeline() -> Optional[RoundPipeline]:
    return _geo_pipe


def drain_async_rounds(timeout: Optional[float] = None) -> bool:
    """Flush the staleness pipes (no-op without one). Call before
    stopping pservers / comparing trainer state — in-flight rounds
    still hold unpushed grads and unconsumed pulls. Covers BOTH the
    sync ps_round pipe and the geo delta pipe."""
    ok = True
    for pipe in (_round_pipe, _geo_pipe):
        if pipe is not None:
            ok = pipe.drain(timeout) and ok
    return ok


def reset_geo_pipeline():
    global _geo_pipe, _geo_pipe_view
    with _round_pipe_lock:
        pipe, _geo_pipe = _geo_pipe, None
        view, _geo_pipe_view = _geo_pipe_view, None
    if view is not None:
        from . import telemetry
        telemetry.REGISTRY.unregister_view(view)
    if pipe is not None:
        pipe.stop(timeout=5.0)


def reset_round_pipeline():
    global _round_pipe, _round_pipe_view
    with _round_pipe_lock:
        pipe, _round_pipe = _round_pipe, None
        view, _round_pipe_view = _round_pipe_view, None
    if view is not None:
        from . import telemetry
        telemetry.REGISTRY.unregister_view(view)
    if pipe is not None:
        pipe.stop(timeout=5.0)
    reset_geo_pipeline()


class Communicator:
    """Fully-async grad plane (``sync_mode=False``; reference
    AsyncCommunicator::SendThread/RecvThread, communicator.h:237).

    Staleness is UNBOUNDED by design: pushes enqueue onto per-var
    merge queues that never gate on an AckWindow — the trainer's step
    is never blocked by the wire, and the server applies whatever
    arrives whenever it arrives (listen_and_serv distributed_mode=1
    applies on arrival). The price is the async consistency model:
    loss tracks the sync oracle's NEIGHBORHOOD, not its trajectory
    (docs/FAULT_TOLERANCE.md "Streaming online learning").

    Every background failure is typed and counted (``stats()``,
    ``ps_communicator`` metrics view): transport outages requeue under
    FLAGS_ps_failover_deadline, server rejections and deadline
    exhaustions drop with distinct counters — nothing is silently
    lost without a counter naming the reason."""

    _global: Optional["Communicator"] = None

    def __init__(self, program=None, mode=None, kwargs=None, envs=None):
        self._running = False
        self._program = program
        self._mode = mode or "async"
        envs = envs or {}
        self._max_merge = int(envs.get("communicator_max_merge_var_num", 20))
        self._wait_times = float(
            envs.get("communicator_send_wait_times", 0.005))
        # independent recv thread cadence (reference
        # independent_recv_thread): how often the background puller
        # refreshes the dense-param double buffer
        self._recv_interval = float(
            envs.get("communicator_independent_recv_interval", 0.05))
        # stop(): how long to wait per merge thread before logging a
        # warning and moving on (env wins, then the FLAG)
        jt = envs.get("communicator_send_join_timeout")
        self._join_timeout = (float(jt) if jt is not None else
                              float(core.globals_[
                                  "FLAGS_communicator_join_timeout"]))
        self._queues: Dict[Tuple[str, str], "queue.Queue"] = {}
        self._threads: list = []
        self._lock = threading.Lock()
        # per-(var, endpoint) first-transport-failure time: merged grads
        # REQUEUE during an endpoint outage (a failover promotes the
        # replica within ~2× the heartbeat timeout and the slot resolves
        # there) and only drop once FLAGS_ps_failover_deadline passed —
        # the pre-elastic behavior silently lost the round's grads
        self._fail_since: Dict[Tuple[str, str], float] = {}
        # stop() flushes queues in SUBMIT order: first-push sequence per
        # (var, endpoint) key — deterministic, matches the order the
        # trainer first produced each grad stream
        self._first_seq: Dict[Tuple[str, str], int] = {}
        self._push_seq = 0
        # typed-and-counted background outcomes; read via stats() and
        # the ps_communicator telemetry view registered on start()
        self._stats_lock = threading.Lock()
        self._stats = {
            "pushes_total": 0,            # grads enqueued by send ops
            "merged_sends_total": 0,      # flush RPCs issued
            "vars_sent_total": 0,         # vars across those flushes
            "dgc_sends_total": 0,         # vars shipped top-k on async path
            "send_ok_total": 0,
            "send_retry_total": 0,        # typed: transport/stale-view
            "requeued_grads_total": 0,    # grads put back during outage
            "dropped_rejected_total": 0,  # typed: server rejected content
            "dropped_deadline_total": 0,  # typed: failover deadline passed
            "recv_rounds_total": 0,       # background recv-thread pulls
            "recv_errors_total": 0,       # typed: recv pull failed
            "stop_flushes_total": 0,
        }
        # independent recv plane: registered pull set + double buffer
        self._recv_lock = threading.Lock()
        self._recv_set: Optional[list] = None   # [(name, ep)]
        self._recv_tid = 0
        self._recv_thread: Optional[threading.Thread] = None
        self._recv_buf = (-1, None)   # (seq, {name: ndarray})
        self._recv_installed = -1
        self._recv_primed = False     # first recv op primed synchronously
        self._view = None

    def _bump(self, key: str, n: int = 1):
        with self._stats_lock:
            self._stats[key] += n

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        with self._lock:
            out["queued_now"] = sum(q.qsize()
                                    for q in self._queues.values())
        out["running"] = bool(self._running)
        return out

    # ---------------------------------------------------------- lifecycle
    def start(self):
        self._running = True
        Communicator._global = self
        if self._view is None:
            from . import telemetry
            self._view = telemetry.REGISTRY.register_view(
                "ps_communicator", self.stats)

    def stop(self):
        # a stop racing an in-flight async-overlap window must drain
        # the staleness pipe FIRST, in FIFO submit order: the pipe's
        # rounds still hold unpushed grads and barrier arrivals the
        # server is counting on, and the merge-queue flush below
        # assumes SYNC rounds (no round may land AFTER the flush, or
        # the server's round accounting sees a phantom late send).
        # Deterministic order = the single pipeline worker's FIFO; the
        # drain is bounded so a wedged round (dead pserver) degrades to
        # the same warn-and-continue contract as the merge threads.
        pipe = _round_pipe
        if pipe is not None:
            try:
                if not pipe.drain(timeout=max(self._join_timeout * 10,
                                              10.0)):
                    _LOG.warning(
                        "Communicator.stop: async round pipe still has "
                        "%d round(s) in flight after the drain timeout "
                        "— a pserver is unreachable; their grads/pulls "
                        "are dropped", pipe.inflight())
            except BaseException as e:  # noqa: BLE001 — stop() finishes
                _LOG.warning(
                    "Communicator.stop: deferred async-round error "
                    "surfaced during the pre-flush drain: %r", e)
        self._running = False
        if Communicator._global is self:
            Communicator._global = None
        rt = self._recv_thread
        if rt is not None and rt.is_alive():
            rt.join(timeout=self._join_timeout)
        for t in self._threads:
            t.join(timeout=self._join_timeout)
            if t.is_alive():
                # a leaked thread means a send is wedged (dead pserver,
                # RPC retry loop) — name it so the operator can tell
                # WHICH var/endpoint queue is stuck
                _LOG.warning(
                    "Communicator.stop: merge thread %r still running "
                    "after %.1fs join timeout — a send to its endpoint "
                    "is wedged; its queued grads may be dropped",
                    t.name, self._join_timeout)
        # flush whatever is still queued — fully, not just one merge
        # batch, in SUBMIT order (first-push sequence per queue): the
        # pserver sees the tail of the stream in the same order the
        # trainer produced it, so a final-state comparison right after
        # stop() is deterministic. Snapshot under the lock and bound
        # the loop so a misbehaving producer still pushing during
        # stop() can't spin this forever.
        with self._lock:
            snapshot = dict(self._queues)
            order = sorted(snapshot,
                           key=lambda k: self._first_seq.get(k, 0))
        for key in order:
            q = snapshot[key]
            flushes = 0
            while not q.empty() and flushes < 1000:
                self._drain(key)
                flushes += 1
                self._bump("stop_flushes_total")
        with self._lock:
            # drop queues so a later start()/push() spawns fresh merge
            # threads (the old ones exited when _running went False)
            self._queues.clear()
            self._threads.clear()
            self._first_seq.clear()
        with self._recv_lock:
            self._recv_set = None
            self._recv_thread = None
            self._recv_buf = (-1, None)
            self._recv_installed = -1
            self._recv_primed = False
        if self._view is not None:
            from . import telemetry
            telemetry.REGISTRY.unregister_view(self._view)
            self._view = None

    def is_running(self):
        return self._running

    @classmethod
    def global_instance(cls) -> Optional["Communicator"]:
        c = cls._global
        return c if c is not None and c._running else None

    # ------------------------------------------------------------- queues
    def push(self, name: str, value, endpoint: str, trainer_id: int = 0):
        """Called by the async send op: enqueue one gradient; a per-var
        daemon merges and sends (reference AsyncCommunicator::Send)."""
        key = (name, endpoint)
        with self._lock:
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = queue.Queue()
                t = threading.Thread(
                    target=self._merge_loop, args=(key, trainer_id),
                    name=f"communicator-merge-{name}@{endpoint}",
                    daemon=True)
                t.start()
                self._threads.append(t)
            self._push_seq += 1
            self._first_seq.setdefault(key, self._push_seq)
        self._bump("pushes_total")
        q.put(np.asarray(value))

    def _send_merged(self, name, ep, merged, trainer_id) -> str:
        """Ship one merged grad; a failure warns instead of killing the
        merge thread (a dead thread would silently pin the queue and
        every later grad). Returns _send_batch's "ok"/"retry"/"drop" —
        the stop()-time flush ignores it (no requeue while stopping)."""
        return self._send_batch(ep, [(name, merged)], trainer_id)

    def _send_batch(self, ep, items, trainer_id) -> str:
        """Ship one coalesced flush: a single-var batch goes out as the
        plain ``send_var`` every server understands; multiple vars for
        the same endpoint ride ONE ``send_vars_batch`` RPC (the server
        applies the whole batch under its grad lock, and the call's
        dedup token covers all of it). An OLD server without the batch
        method falls back to per-var sends (ps_rpc.send_vars_batch —
        only on "no method", when nothing was applied; a PARTIALLY
        applied batch must not be re-sent per-var).

        Returns "ok" | "retry" (transport failure — the endpoint may be
        failing over to a promoted replica, requeue) | "drop" (the
        server REJECTED the content; re-sending the same grads would
        just be rejected again)."""
        from .ps_rpc import VarClient, send_vars_batch
        names = [n for n, _ in items]
        try:
            if len(items) == 1:
                VarClient.of(ep).send_var(names[0], items[0][1],
                                          trainer_id=trainer_id)
            else:
                send_vars_batch(VarClient.of(ep), items,
                                trainer_id=trainer_id)
            self._bump("send_ok_total")
            self._bump("merged_sends_total")
            self._bump("vars_sent_total", len(items))
            return "ok"
        except (ConnectionError, OSError) as e:
            _LOG.warning(
                "Communicator: merged grads %s for %s undeliverable — "
                "endpoint unreachable after RPC retries (%r)", names, ep, e)
            self._bump("send_retry_total")
            return "retry"
        except core.StaleClusterViewError as e:
            # the call's re-route budget ran out while membership was
            # still converging (a drain racing a failover) — NOT a
            # content rejection: the views settle moments later, so
            # requeue like a transport outage instead of silently
            # losing the round's merged grads
            _LOG.warning(
                "Communicator: merged grads %s for %s caught a "
                "stale-view convergence window (%r) — requeueing",
                names, ep, e)
            self._bump("send_retry_total")
            return "retry"
        except Exception as e:  # noqa: BLE001 — server-side rejection
            _LOG.warning(
                "Communicator: dropping merged grads %s for %s — "
                "server rejected them (%r)", names, ep, e)
            self._bump("dropped_rejected_total", len(items))
            return "drop"

    def _send_dgc(self, ep, name, merged, trainer_id):
        """Ship one merged grad top-k compressed on the async path
        (FLAGS_dgc; the same dgc_send frame the sync _push_dense_batch
        lane uses). compress() folds the grad into the error-feedback
        residual and zeroes the selection, so a transport failure must
        RESTORE the mass before requeueing — restore_dense() hands the
        full accumulator back and clears the residual, and the caller
        requeues that dense payload (re-compressed at the next flush:
        mass is conserved across the outage, momentum state resets —
        acceptable under an outage, documented contract). Returns
        (outcome, requeue_payload_or_None): "sent" | "pass" (not
        eligible / old server — caller ships dense) | "retry" |
        "drop"."""
        from .ps_rpc import VarClient
        g = np.asarray(merged)
        cli = VarClient.of(ep)
        if "dgc_send" in cli._missing_methods:
            return "pass", None
        comp = dgc_compressor()
        enc = comp.compress(name, g)
        if enc is None:
            return "pass", None
        idx, vals = enc
        try:
            cli.call("dgc_send", name=name, values=vals, indices=idx,
                     shape=list(g.shape), trainer_id=trainer_id)
            self._bump("dgc_sends_total")
            return "sent", None
        except (ConnectionError, OSError, core.StaleClusterViewError) as e:
            full = comp.restore_dense(name, idx, vals)
            _LOG.warning(
                "Communicator: dgc push %s for %s undeliverable (%r) — "
                "restored residual, requeueing dense", name, ep, e)
            return "retry", full.reshape(g.shape)
        except Exception as e:  # noqa: BLE001 — old server / rejection
            if "no method dgc_send" in str(e):
                cli._missing_methods.add("dgc_send")
                full = comp.restore_dense(name, idx, vals)
                return "pass", full.reshape(g.shape)
            _LOG.warning(
                "Communicator: dropping dgc push %s for %s — server "
                "rejected it (%r)", name, ep, e)
            return "drop", None

    def _drain(self, key, trainer_id=0):
        name, ep = key
        merged = self._drain_nowait(key)
        if merged is not None:
            self._send_merged(name, ep, merged, trainer_id)

    def _drain_nowait(self, key):
        """Merge whatever is queued for ``key`` right now (no waiting);
        None when its queue is empty."""
        q = self._queues.get(key)
        if q is None:
            return None
        merged, n = None, 0
        while n < self._max_merge:
            try:
                v = q.get_nowait()
            except queue.Empty:
                break
            merged = v if merged is None else merged + v
            n += 1
        return merged

    def _merge_loop(self, key, trainer_id):
        name, ep = key
        q = self._queues[key]
        while self._running:
            try:
                first = q.get(timeout=self._wait_times * 10)
            except queue.Empty:
                continue
            merged = np.asarray(first)
            n = 1
            # short grace window lets a burst of pending grads coalesce
            deadline = threading.Event()
            deadline.wait(self._wait_times)
            while n < self._max_merge:
                try:
                    merged = merged + q.get_nowait()
                    n += 1
                except queue.Empty:
                    break
            # coalesced flush: piggyback OTHER vars pending for the same
            # endpoint onto this send (one multi-var RPC instead of one
            # RPC per var — the reference AsyncCommunicator's batched
            # send queues). queue.get_nowait is atomic, so a concurrent
            # sibling merge thread never double-takes a grad. The legacy
            # data-plane lane (PADDLE_TPU_PS_PICKLE_WIRE=1) keeps the
            # pre-overhaul one-RPC-per-var behavior.
            from .ps_rpc import _pickle_wire_forced
            batch = [(name, merged)]
            if not _pickle_wire_forced():
                with self._lock:
                    siblings = [k for k in self._queues
                                if k[1] == ep and k != key]
                for k in siblings:
                    other = self._drain_nowait(k)
                    if other is not None:
                        batch.append((k[0], other))
            # FLAGS_dgc: eligible merged grads ship as top-k dgc_send
            # frames right here on the async path (the sync lane does
            # this in _push_dense_batch); the rest — plus any restored
            # dense fallbacks — ride the coalesced batch send below
            send_items, requeue_now = [], []
            if dgc_enabled() and not _pickle_wire_forced():
                for n, v in batch:
                    oc, payload = self._send_dgc(ep, n, v, trainer_id)
                    if oc == "sent":
                        continue
                    if oc == "pass":
                        send_items.append(
                            (n, v if payload is None else payload))
                    elif oc == "retry":
                        requeue_now.append((n, payload))
                    # "drop": counted in _send_dgc's rejection path
            else:
                send_items = batch
            outcome = "ok"
            if send_items:
                outcome = self._send_batch(ep, send_items, trainer_id)
            to_requeue = list(requeue_now)
            if outcome == "retry":
                to_requeue.extend(send_items)
            if to_requeue and self._running:
                # endpoint outage (possibly a failover in progress):
                # requeue every merged grad onto its own queue — the
                # NEXT flush re-resolves the slot and reaches the
                # promoted replica. Give up only past the failover
                # deadline; a permanently dead endpoint must not spin
                # the thread and pin stale grads forever.
                import time as _time
                now = _time.time()
                first = self._fail_since.setdefault(key, now)
                limit = float(core.globals_["FLAGS_ps_failover_deadline"])
                if now - first <= limit:
                    for n, v in to_requeue:
                        self.push(n, v, ep, trainer_id=trainer_id)
                    self._bump("requeued_grads_total", len(to_requeue))
                    # breathe: don't hot-loop against a dead endpoint
                    threading.Event().wait(self._wait_times * 10)
                else:
                    _LOG.warning(
                        "Communicator: giving up on %s after %.0fs of "
                        "transport failures — dropping %d merged "
                        "grad(s)", ep, now - first,
                        len(to_requeue))
                    self._bump("dropped_deadline_total", len(to_requeue))
                    self._fail_since.pop(key, None)
            elif outcome != "retry":
                # "ok" AND "drop" both end the outage streak ("drop" =
                # the server was reachable and rejected): a stale
                # first-failure stamp would make a later unrelated
                # outage give up on its first "retry" instead of
                # requeueing through the failover window
                self._fail_since.pop(key, None)

    # --------------------------------------------------- independent recv
    # reference AsyncCommunicator::RecvThread: in async mode the trainer
    # never blocks a step on a param pull — a background thread refreshes
    # a double buffer at _recv_interval and the recv op installs the
    # newest completed buffer at the next step boundary (same protocol
    # as RoundPipeline.take_fresh_pulls). Registration happens lazily
    # from the first recv op execution, which knows the (param, ep) set.

    def register_recv(self, pairs, trainer_id: int = 0):
        """Register the async pull set [(param_name, endpoint)] and
        start the recv thread (idempotent)."""
        with self._recv_lock:
            merged = dict(self._recv_set or [])
            merged.update(dict(pairs))
            self._recv_set = sorted(merged.items())
            self._recv_tid = int(trainer_id)
            if self._recv_thread is None or \
                    not self._recv_thread.is_alive():
                self._recv_thread = threading.Thread(
                    target=self._recv_loop, name="communicator-recv",
                    daemon=True)
                self._recv_thread.start()

    def take_fresh_recv(self):
        """Newest completed background pull, handed out exactly once
        (None when the trainer already installed it)."""
        with self._recv_lock:
            seq, buf = self._recv_buf
            if buf is None or seq <= self._recv_installed:
                return None
            self._recv_installed = seq
            return buf

    def _pull_once(self, pairs, tid) -> dict:
        """Fetch every registered param once; an unreachable endpoint
        skips its params for THIS refresh only (the trainer keeps the
        last installed values — bounded staleness, never a crash) and
        is typed + counted."""
        from .ps_rpc import VarClient
        by_ep: Dict[str, list] = {}
        for n, ep in pairs:
            by_ep.setdefault(ep, []).append(n)
        buf = {}
        for ep, names in by_ep.items():
            cli = VarClient.of(ep)
            try:
                if len(names) > 1 and \
                        "get_vars_batch" not in cli._missing_methods:
                    try:
                        got = cli.call("get_vars_batch", names=names,
                                       trainer_id=tid)
                    except RuntimeError as e:
                        if "no method get_vars_batch" not in str(e):
                            raise
                        cli._missing_methods.add("get_vars_batch")
                        got = [cli.get_var(n, trainer_id=tid)
                               for n in names]
                else:
                    got = [cli.get_var(n, trainer_id=tid) for n in names]
                for n, v in zip(names, got):
                    buf[n] = np.asarray(v)
            except Exception as e:  # noqa: BLE001 — typed + counted
                self._bump("recv_errors_total")
                _LOG.warning(
                    "Communicator: background recv from %s failed "
                    "(%r) — keeping last installed params", ep, e)
        return buf

    def _recv_loop(self):
        seq = 0
        while self._running:
            threading.Event().wait(self._recv_interval)
            if not self._running:
                return
            with self._recv_lock:
                pairs, tid = self._recv_set, self._recv_tid
            if not pairs:
                continue
            buf = self._pull_once(pairs, tid)
            if buf:
                seq += 1
                with self._recv_lock:
                    self._recv_buf = (seq, buf)
                self._bump("recv_rounds_total")

    def recv(self) -> dict:
        """One synchronous pull of the registered set (start-up priming
        / tests); returns the buffer without touching the double-buffer
        seq accounting."""
        with self._recv_lock:
            pairs, tid = self._recv_set, self._recv_tid
        return self._pull_once(pairs or [], tid)


class LargeScaleKV:
    """Host-RAM key→row store stub (reference large_scale_kv.h); the
    pserver scope already hosts whole tables in this build."""

    def __init__(self):
        self._store = {}

    def save(self, name, path):
        import numpy as np
        np.save(path, self._store.get(name))

    def size(self, name):
        v = self._store.get(name)
        return 0 if v is None else len(v)
