"""Communicator — async grad merge/send threads for PS training
(reference: python/paddle/fluid/communicator.py:27,91 wrapping C++
operators/distributed/communicator.h — AsyncCommunicator:237 merge queues,
HalfAsyncCommunicator:299, GeoCommunicator:383).

TPU framing: in this build the async PS plane applies updates server-side
on arrival (ops/distributed_ops.py listen_and_serv), so per-grad client
merge queues collapse to an optional batching thread. The API surface
(start/stop/is_running) is kept for fleet parity; SYNC mode needs no
communicator at all (send/recv ops carry the traffic in-program)."""
from __future__ import annotations

import threading

__all__ = ["Communicator", "LargeScaleKV"]


class Communicator:
    def __init__(self, program=None, mode=None, kwargs=None, envs=None):
        self._running = False
        self._program = program

    def start(self):
        self._running = True

    def stop(self):
        self._running = False

    def is_running(self):
        return self._running

    def recv(self):
        pass


class LargeScaleKV:
    """Host-RAM key→row store stub (reference large_scale_kv.h); the
    pserver scope already hosts whole tables in this build."""

    def __init__(self):
        self._store = {}

    def save(self, name, path):
        import numpy as np
        np.save(path, self._store.get(name))

    def size(self, name):
        v = self._store.get(name)
        return 0 if v is None else len(v)
