"""Communicator — async grad merge/send threads for PS training
(reference: python/paddle/fluid/communicator.py:27,91 wrapping C++
operators/distributed/communicator.h — AsyncCommunicator:237 merge queues,
HalfAsyncCommunicator:299, GeoCommunicator:383).

TPU framing: the pserver applies updates on arrival
(ops/distributed_ops.py listen_and_serv async loop), so correctness never
needs client-side queues — but the reference's merge behavior matters for
RPC load: with a running Communicator, async-mode send ops enqueue grads
here instead of issuing one RPC each; per-var merge threads sum up to
``max_merge_var_num`` pending grads and ship one merged send (the
AsyncCommunicator contract). SYNC mode needs no communicator at all."""
from __future__ import annotations

import logging
import queue
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from . import core

__all__ = ["Communicator", "LargeScaleKV"]

_LOG = logging.getLogger("paddle_tpu.ps")


class Communicator:
    _global: Optional["Communicator"] = None

    def __init__(self, program=None, mode=None, kwargs=None, envs=None):
        self._running = False
        self._program = program
        envs = envs or {}
        self._max_merge = int(envs.get("communicator_max_merge_var_num", 20))
        self._wait_times = float(
            envs.get("communicator_send_wait_times", 0.005))
        # stop(): how long to wait per merge thread before logging a
        # warning and moving on (env wins, then the FLAG)
        jt = envs.get("communicator_send_join_timeout")
        self._join_timeout = (float(jt) if jt is not None else
                              float(core.globals_[
                                  "FLAGS_communicator_join_timeout"]))
        self._queues: Dict[Tuple[str, str], "queue.Queue"] = {}
        self._threads: list = []
        self._lock = threading.Lock()
        # per-(var, endpoint) first-transport-failure time: merged grads
        # REQUEUE during an endpoint outage (a failover promotes the
        # replica within ~2× the heartbeat timeout and the slot resolves
        # there) and only drop once FLAGS_ps_failover_deadline passed —
        # the pre-elastic behavior silently lost the round's grads
        self._fail_since: Dict[Tuple[str, str], float] = {}

    # ---------------------------------------------------------- lifecycle
    def start(self):
        self._running = True
        Communicator._global = self

    def stop(self):
        self._running = False
        if Communicator._global is self:
            Communicator._global = None
        for t in self._threads:
            t.join(timeout=self._join_timeout)
            if t.is_alive():
                # a leaked thread means a send is wedged (dead pserver,
                # RPC retry loop) — name it so the operator can tell
                # WHICH var/endpoint queue is stuck
                _LOG.warning(
                    "Communicator.stop: merge thread %r still running "
                    "after %.1fs join timeout — a send to its endpoint "
                    "is wedged; its queued grads may be dropped",
                    t.name, self._join_timeout)
        # flush whatever is still queued — fully, not just one merge batch.
        # Snapshot under the lock and bound the loop so a misbehaving
        # producer still pushing during stop() can't spin this forever.
        with self._lock:
            snapshot = dict(self._queues)
        for key, q in snapshot.items():
            flushes = 0
            while not q.empty() and flushes < 1000:
                self._drain(key)
                flushes += 1
        with self._lock:
            # drop queues so a later start()/push() spawns fresh merge
            # threads (the old ones exited when _running went False)
            self._queues.clear()
            self._threads.clear()

    def is_running(self):
        return self._running

    @classmethod
    def global_instance(cls) -> Optional["Communicator"]:
        c = cls._global
        return c if c is not None and c._running else None

    # ------------------------------------------------------------- queues
    def push(self, name: str, value, endpoint: str, trainer_id: int = 0):
        """Called by the async send op: enqueue one gradient; a per-var
        daemon merges and sends (reference AsyncCommunicator::Send)."""
        key = (name, endpoint)
        with self._lock:
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = queue.Queue()
                t = threading.Thread(
                    target=self._merge_loop, args=(key, trainer_id),
                    name=f"communicator-merge-{name}@{endpoint}",
                    daemon=True)
                t.start()
                self._threads.append(t)
        q.put(np.asarray(value))

    def _send_merged(self, name, ep, merged, trainer_id) -> str:
        """Ship one merged grad; a failure warns instead of killing the
        merge thread (a dead thread would silently pin the queue and
        every later grad). Returns _send_batch's "ok"/"retry"/"drop" —
        the stop()-time flush ignores it (no requeue while stopping)."""
        return self._send_batch(ep, [(name, merged)], trainer_id)

    def _send_batch(self, ep, items, trainer_id) -> str:
        """Ship one coalesced flush: a single-var batch goes out as the
        plain ``send_var`` every server understands; multiple vars for
        the same endpoint ride ONE ``send_vars_batch`` RPC (the server
        applies the whole batch under its grad lock, and the call's
        dedup token covers all of it). An OLD server without the batch
        method falls back to per-var sends (ps_rpc.send_vars_batch —
        only on "no method", when nothing was applied; a PARTIALLY
        applied batch must not be re-sent per-var).

        Returns "ok" | "retry" (transport failure — the endpoint may be
        failing over to a promoted replica, requeue) | "drop" (the
        server REJECTED the content; re-sending the same grads would
        just be rejected again)."""
        from .ps_rpc import VarClient, send_vars_batch
        names = [n for n, _ in items]
        try:
            if len(items) == 1:
                VarClient.of(ep).send_var(names[0], items[0][1],
                                          trainer_id=trainer_id)
            else:
                send_vars_batch(VarClient.of(ep), items,
                                trainer_id=trainer_id)
            return "ok"
        except (ConnectionError, OSError) as e:
            _LOG.warning(
                "Communicator: merged grads %s for %s undeliverable — "
                "endpoint unreachable after RPC retries (%r)", names, ep, e)
            return "retry"
        except core.StaleClusterViewError as e:
            # the call's re-route budget ran out while membership was
            # still converging (a drain racing a failover) — NOT a
            # content rejection: the views settle moments later, so
            # requeue like a transport outage instead of silently
            # losing the round's merged grads
            _LOG.warning(
                "Communicator: merged grads %s for %s caught a "
                "stale-view convergence window (%r) — requeueing",
                names, ep, e)
            return "retry"
        except Exception as e:  # noqa: BLE001 — server-side rejection
            _LOG.warning(
                "Communicator: dropping merged grads %s for %s — "
                "server rejected them (%r)", names, ep, e)
            return "drop"

    def _drain(self, key, trainer_id=0):
        name, ep = key
        merged = self._drain_nowait(key)
        if merged is not None:
            self._send_merged(name, ep, merged, trainer_id)

    def _drain_nowait(self, key):
        """Merge whatever is queued for ``key`` right now (no waiting);
        None when its queue is empty."""
        q = self._queues.get(key)
        if q is None:
            return None
        merged, n = None, 0
        while n < self._max_merge:
            try:
                v = q.get_nowait()
            except queue.Empty:
                break
            merged = v if merged is None else merged + v
            n += 1
        return merged

    def _merge_loop(self, key, trainer_id):
        name, ep = key
        q = self._queues[key]
        while self._running:
            try:
                first = q.get(timeout=self._wait_times * 10)
            except queue.Empty:
                continue
            merged = np.asarray(first)
            n = 1
            # short grace window lets a burst of pending grads coalesce
            deadline = threading.Event()
            deadline.wait(self._wait_times)
            while n < self._max_merge:
                try:
                    merged = merged + q.get_nowait()
                    n += 1
                except queue.Empty:
                    break
            # coalesced flush: piggyback OTHER vars pending for the same
            # endpoint onto this send (one multi-var RPC instead of one
            # RPC per var — the reference AsyncCommunicator's batched
            # send queues). queue.get_nowait is atomic, so a concurrent
            # sibling merge thread never double-takes a grad. The legacy
            # data-plane lane (PADDLE_TPU_PS_PICKLE_WIRE=1) keeps the
            # pre-overhaul one-RPC-per-var behavior.
            from .ps_rpc import _pickle_wire_forced
            batch = [(name, merged)]
            if not _pickle_wire_forced():
                with self._lock:
                    siblings = [k for k in self._queues
                                if k[1] == ep and k != key]
                for k in siblings:
                    other = self._drain_nowait(k)
                    if other is not None:
                        batch.append((k[0], other))
            outcome = self._send_batch(ep, batch, trainer_id)
            if outcome == "retry" and self._running:
                # endpoint outage (possibly a failover in progress):
                # requeue every merged grad onto its own queue — the
                # NEXT flush re-resolves the slot and reaches the
                # promoted replica. Give up only past the failover
                # deadline; a permanently dead endpoint must not spin
                # the thread and pin stale grads forever.
                import time as _time
                now = _time.time()
                first = self._fail_since.setdefault(key, now)
                limit = float(core.globals_["FLAGS_ps_failover_deadline"])
                if now - first <= limit:
                    for n, v in batch:
                        self.push(n, v, ep, trainer_id=trainer_id)
                    # breathe: don't hot-loop against a dead endpoint
                    threading.Event().wait(self._wait_times * 10)
                else:
                    _LOG.warning(
                        "Communicator: giving up on %s after %.0fs of "
                        "transport failures — dropping %d merged "
                        "grad(s)", ep, now - first,
                        len(batch))
                    self._fail_since.pop(key, None)
            else:
                # "ok" AND "drop" both end the outage streak ("drop" =
                # the server was reachable and rejected): a stale
                # first-failure stamp would make a later unrelated
                # outage give up on its first "retry" instead of
                # requeueing through the failover window
                self._fail_since.pop(key, None)

    def recv(self):
        pass


class LargeScaleKV:
    """Host-RAM key→row store stub (reference large_scale_kv.h); the
    pserver scope already hosts whole tables in this build."""

    def __init__(self):
        self._store = {}

    def save(self, name, path):
        import numpy as np
        np.save(path, self._store.get(name))

    def size(self, name):
        v = self._store.get(name)
        return 0 if v is None else len(v)
