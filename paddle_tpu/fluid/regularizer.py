"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py)."""
from __future__ import annotations

from .layer_helper import LayerHelper

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = float(regularization_coeff)

    def __call__(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(param.dtype)
        decay.shape = param.shape
        block.append_op(type="scale", inputs={"X": [param]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self._coeff, "op_role": 1})
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = float(regularization_coeff)

    def __call__(self, param, grad, block):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(param.dtype)
        sign.shape = param.shape
        decay = helper.create_variable_for_type_inference(param.dtype)
        decay.shape = param.shape
        block.append_op(type="sign", inputs={"X": [param]},
                        outputs={"Out": [sign]}, attrs={"op_role": 1})
        block.append_op(type="scale", inputs={"X": [sign]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self._coeff, "op_role": 1})
        return decay


def append_regularization_ops(parameters_and_grads, regularization=None):
    """reference regularizer.py append_regularization_ops: grad += decay."""
    from .layer_helper import LayerHelper
    res = []
    for param, grad in parameters_and_grads:
        if grad is None:
            res.append((param, grad))
            continue
        reg = param.regularizer if param.regularizer is not None \
            else regularization
        if reg is None:
            res.append((param, grad))
            continue
        block = grad.block
        decay = reg(param, grad, block)
        new_grad = block.create_var(
            name=grad.name + "@REGULARIZED",
            dtype=grad.dtype, shape=grad.shape, persistable=False)
        block.append_op(type="sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [new_grad]}, attrs={"op_role": 1})
        res.append((param, new_grad))
    return res


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
