"""Autodiff: append_backward (reference: python/paddle/fluid/backward.py:1193).

Walks forward ops in reverse emitting ``<op>_grad`` OpDescs with the
reference slot convention (inputs = fwd inputs + fwd outputs + Out@GRAD
slots; outputs = X@GRAD slots; empty slots use the @EMPTY@ sentinel), sums
fan-in gradients (reference _addup_repetitive_outputs_), and prunes ops not
on the loss→parameter path.

Grad semantics come from each op's registered grad maker, or mechanically
from the forward kernel via jax.vjp (ops/registry.py run_generic_grad) —
the emitted grad op records its forward-input slot names in the ``_fwd_in``
attr so the executor can reconstruct the vjp closure. Under jit, forward
re-trace inside vjp is deduplicated by XLA CSE, so this costs nothing.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from .framework import (Block, Operator, Parameter, Program, Variable,
                        default_main_program, grad_var_name)
from ..ops.registry import OPS

__all__ = ["append_backward", "gradients", "calc_gradient"]

EMPTY_VAR = "@EMPTY@"

# op_role values (reference: framework/op_proto_maker.h OpRole)
OP_ROLE_FORWARD = 0
OP_ROLE_BACKWARD = 1
OP_ROLE_OPTIMIZE = 2
OP_ROLE_LOSS = 256


def _op_no_grad(op_type: str) -> bool:
    if OPS.has(op_type):
        info = OPS.get(op_type)
        return info.no_grad and info.grad_maker is None
    if op_type.endswith("_grad") and op_type != "_grad":
        # a grad op is differentiable iff its base is (static double
        # grad: gradient-penalty sweeps differentiate *_grad ops)
        return _op_no_grad(op_type[:-5])
    return True


def _find_loss_op(block: Block, loss: Variable) -> int:
    for i in range(len(block.ops) - 1, -1, -1):
        if loss.name in block.ops[i].output_arg_names:
            return i
    raise ValueError(f"loss var {loss.name} not produced in block")


def _vars_requiring_grad(block: Block, ops: List[Operator],
                         no_grad_set: Set[str]) -> Set[str]:
    """Forward propagation of requires-grad from trainable params/inputs."""
    req: Set[str] = set()
    for v in block.vars.values():
        if isinstance(v, Parameter) and v.trainable and v.name not in no_grad_set:
            req.add(v.name)
        elif not v.stop_gradient and v.name not in no_grad_set:
            # any var with stop_gradient=False is a grad leaf/carrier
            # (reference backward.py semantics)
            req.add(v.name)
    for op in ops:
        if _op_no_grad(op.type):
            continue
        if any(n in req for n in op.input_arg_names):
            for n in op.output_arg_names:
                v = block.vars.get(n)
                if v is None or not v.stop_gradient:
                    if n not in no_grad_set:
                        req.add(n)
    return req


def _ops_on_path(ops: List[Operator], loss_name: str,
                 req: Set[str]) -> List[int]:
    """Indices of ops contributing to loss AND touched by requires-grad."""
    needed = {loss_name}
    keep = []
    for i in range(len(ops) - 1, -1, -1):
        op = ops[i]
        if any(n in needed for n in op.output_arg_names):
            keep.append(i)
            needed.update(op.input_arg_names)
    return sorted(keep)


def _default_grad_op_descs(op: Operator, grad_map: Dict[str, str],
                           req: Set[str], no_grad_set: Set[str]):
    """Build the generic ``<op>_grad`` desc for a forward op."""
    info = OPS.get(op.type) if OPS.has(op.type) else None
    inputs: Dict[str, List[str]] = {}
    for slot, names in op.inputs.items():
        inputs[slot] = list(names)
    for slot, names in op.outputs.items():
        if slot in inputs:
            continue
        inputs[slot] = list(names)
    has_any_ograd = False
    for slot, names in op.outputs.items():
        gnames = []
        for n in names:
            g = grad_map.get(n)
            gnames.append(g if g is not None else EMPTY_VAR)
            if g is not None:
                has_any_ograd = True
        inputs[slot + "@GRAD"] = gnames
    if not has_any_ograd:
        return None

    outputs: Dict[str, List[str]] = {}
    allowed = set(info.diff_input_slots) if (info and info.diff_input_slots) \
        else None
    produced = []
    for slot, names in op.inputs.items():
        if allowed is not None and slot not in allowed:
            continue
        gnames = []
        any_real = False
        for n in names:
            if n in req and n not in no_grad_set:
                gnames.append(grad_var_name(n))
                any_real = True
                produced.append(n)
            else:
                gnames.append(EMPTY_VAR)
        if any_real:
            outputs[slot + "@GRAD"] = gnames
    if not outputs:
        return None
    attrs = {k: v for k, v in op.attrs.items()}
    if "_fwd_in" in attrs:
        # differentiating a *_grad op: keep the BASE op's forward slots
        # for the nested vjp (run_generic_grad_grad) before recording
        # this op's own slots
        attrs.setdefault("_fwd_in_base", attrs["_fwd_in"])
    attrs["_fwd_in"] = list(op.inputs.keys())
    return [{"type": op.type + "_grad", "inputs": inputs,
             "outputs": outputs, "attrs": attrs}], produced


def _mark_fwd_idx(descs, fwd_idx):
    """Record the forward op's block index on its grad descs so the
    executor re-derives the SAME per-op PRNG key when a vjp grad re-runs a
    needs_rng forward kernel (sampling ops: nce, sampled softmax, …)."""
    for d in descs:
        d["attrs"].setdefault("_fwd_idx", fwd_idx)
    return descs


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """reference backward.py:1193 — returns [(param, grad_var), ...]."""
    program = loss.block.program
    block = loss.block
    no_grad = set()
    if no_grad_set:
        no_grad.update(v.name if isinstance(v, Variable) else v
                       for v in no_grad_set)
    for v in block.vars.values():
        if v.stop_gradient and not isinstance(v, Parameter):
            no_grad.add(v.name)

    loss_idx = _find_loss_op(block, loss)
    fwd_ops = block.ops[:loss_idx + 1]
    req = _vars_requiring_grad(block, fwd_ops, no_grad)
    req.add(loss.name)
    path = set(_ops_on_path(fwd_ops, loss.name, req))

    # mark the loss op
    block.ops[loss_idx].attrs.setdefault("op_role", OP_ROLE_LOSS)

    # seed: d loss / d loss = 1
    grad_map: Dict[str, str] = {loss.name: grad_var_name(loss.name)}
    block.append_op(
        type="fill_constant", inputs={},
        outputs={"Out": [grad_var_name(loss.name)]},
        attrs={"shape": [1], "value": 1.0, "dtype": loss.dtype,
               "op_role": OP_ROLE_BACKWARD})
    gv = block.create_var(name=grad_var_name(loss.name), dtype=loss.dtype,
                          shape=(1,), persistable=False)
    gv.stop_gradient = False

    # reverse sweep
    pending_descs = []
    grad_writers: Dict[str, int] = {}
    for i in range(loss_idx, -1, -1):
        if i not in path:
            continue
        op = fwd_ops[i]
        if _op_no_grad(op.type):
            continue
        if not any(n in req and n not in no_grad for n in op.input_arg_names):
            continue
        info = OPS.get(op.type) if OPS.has(op.type) else None
        if info is not None and info.grad_maker is not None:
            # a params-reachable branch no loss-grad flows into (e.g. an
            # auxiliary head outside the fetched loss) reaches custom
            # makers with every output grad EMPTY — apply the generic
            # path's has_any_ograd rule BEFORE the maker runs instead of
            # handing kernels a None cotangent. (Not a desc-level filter:
            # makers like the quant STE emit descs whose grad inputs sit
            # in plain slots such as assign's "X".)
            if not any(n in grad_map for n in op.output_arg_names):
                continue
            descs = info.grad_maker(op, {**{n: grad_map.get(n, EMPTY_VAR)
                                            for n in op.output_arg_names},
                                         **{n: grad_var_name(n)
                                            for n in op.input_arg_names
                                            if n in req and n not in no_grad}})
            if descs is None:
                continue
        else:
            res = _default_grad_op_descs(op, grad_map, req, no_grad)
            if res is None:
                continue
            descs, _produced = res
            if info is not None and info.needs_rng:
                _mark_fwd_idx(descs, i)
        for d in descs:
            pending_descs.append(d)
            # record primal→grad mapping now: grad ops of earlier forward
            # ops (emitted later in this sweep) consume these names
            for slot, names in d["outputs"].items():
                if not slot.endswith("@GRAD"):
                    continue
                primal_slot = slot[:-5]
                fwd_names = d["inputs"].get(primal_slot, [])
                for pn, gn in zip(fwd_names, names):
                    if gn != EMPTY_VAR:
                        grad_map.setdefault(pn, gn)
        # custom makers' descs need not mirror the primal slots (e.g.
        # dropout_grad has no "X" input; the quant STE emits a plain
        # assign) — without this fallback their input grads were never
        # recorded and every op upstream of a dropout/quant silently got
        # EMPTY cotangents (models trained only their heads). The makers
        # receive grads under the grad_var_name convention, so any desc
        # output matching grad_var_name(input) IS that input's grad.
        produced = {n2 for d in descs
                    for ns in d["outputs"].values() for n2 in ns}
        for pn in op.input_arg_names:
            gn = grad_var_name(pn)
            if gn in produced:
                grad_map.setdefault(pn, gn)

    # gradient fan-in: rename duplicate writes, insert sum ops
    write_counts: Dict[str, int] = {}
    for d in pending_descs:
        for slot, names in d["outputs"].items():
            for n in names:
                if n != EMPTY_VAR:
                    write_counts[n] = write_counts.get(n, 0) + 1
    renamed: Dict[str, List[str]] = {}
    for d in pending_descs:
        for slot, names in d["outputs"].items():
            for k, n in enumerate(names):
                if n == EMPTY_VAR or write_counts.get(n, 0) <= 1:
                    continue
                parts = renamed.setdefault(n, [])
                new_name = f"{n}@RENAME@{len(parts)}"
                parts.append(new_name)
                names[k] = new_name

    final_ops: List[dict] = []
    summed: Set[str] = set()
    for d in pending_descs:
        final_ops.append(d)
        # after the op that writes the last part, insert the sum
        for name, parts in renamed.items():
            if name in summed:
                continue
            if parts and parts[-1] in [n for ns in d["outputs"].values()
                                       for n in ns]:
                final_ops.append({"type": "sum", "inputs": {"X": list(parts)},
                                  "outputs": {"Out": [name]}, "attrs": {}})
                summed.add(name)

    # materialize ops + grad vars
    for d in final_ops:
        attrs = dict(d.get("attrs") or {})
        attrs.setdefault("op_role", OP_ROLE_BACKWARD)
        block.append_op(type=d["type"], inputs=d["inputs"],
                        outputs=d["outputs"], attrs=attrs)
        for slot, names in d["outputs"].items():
            for n in names:
                if n == EMPTY_VAR or n in block.vars:
                    continue
                primal = n.split("@GRAD")[0]
                pv = block.vars.get(primal)
                block.create_var(
                    name=n, dtype=pv.dtype if pv else loss.dtype,
                    shape=pv.shape if pv else (), persistable=False)

    # collect params & grads
    if parameter_list is not None:
        params = [block.program.global_block().var(p)
                  if isinstance(p, str) else p for p in parameter_list]
    else:
        params = [v for v in block.program.global_block().all_parameters()
                  if v.trainable]
    result = []
    for p in params:
        gname = grad_var_name(p.name)
        if gname in block.vars:
            result.append((p, block.vars[gname]))
    program._appending_grad_times += 1
    return result


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference backward.py:1599 — grads of targets w.r.t. inputs."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    assert target_gradients is None, "target_gradients: pending"
    # scalar proxy with ones cotangent: sum of reduce_sum(target) gives
    # d(proxy)/d(target) == 1 everywhere (the fluid.gradients contract)
    from .layers import nn as _nn
    loss = None
    for t in targets:
        m = _nn.reduce_sum(t)
        loss = m if loss is None else _nn.elementwise_add(loss, m)
    # requested inputs (often stop_gradient data vars) must join the
    # requires-grad set or no grad ops are emitted for them
    restore = [(iv, iv.stop_gradient) for iv in inputs]
    for iv in inputs:
        iv.stop_gradient = False
    try:
        append_backward(loss, no_grad_set=no_grad_set)
    finally:
        for iv, sg in restore:
            iv.stop_gradient = sg
    block = targets[0].block
    outs = []
    for iv in inputs:
        g = grad_var_name(iv.name)
        outs.append(block.vars.get(g))
    return outs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
