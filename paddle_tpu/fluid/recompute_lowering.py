"""Lower RecomputeOptimizer checkpoints onto jax.checkpoint segments.

The reference rewrites the backward program to re-run forward subgraphs
between user-chosen checkpoint variables so activations inside a segment
are never stored (reference: python/paddle/fluid/optimizer.py
RecomputeOptimizer:3850, backward.py _append_backward_ops_with_
checkpoints_). A plain program-level rewrite would be undone by XLA's
CSE (the recomputed subgraph is identical to the stored one), so the
TPU lowering happens at trace level instead: each forward segment
becomes ONE ``jax.checkpoint``-wrapped function (XLA keeps the
rematerialization barrier), and the segment's backward ops are replaced
by the ``jax.vjp`` of that wrapped function — only the segment-boundary
values stay live between forward and backward.

Lowering preconditions (else fused fallback with a warning — same
numerics, more memory):
  * checkpoints are produced in the main block, no control flow inside
    a segment
  * every external input of a segment (params, earlier activations)
    receives its gradient ONLY from that segment's backward span, and
    the spans are contiguous per segment in reverse order — shared
    params across segments would fan-in through rename/sum ops the span
    classifier cannot split
"""
from __future__ import annotations

import warnings
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from .backward import grad_var_name


class Segment:
    def __init__(self):
        self.ops = []
        self.ins: List[str] = []     # external reads, in first-use order
        self.outs: List[str] = []    # written AND read outside


class RematPlan:
    def __init__(self):
        self.pre_ops = []            # ops before the first segment
        self.segments: List[Segment] = []
        self.rest_head = []          # loss head + its bwd (after last seg)
        self.spans: List[List] = []  # per segment: replaced bwd ops
        self.between: List[List] = []  # rest ops between spans (reverse)
        self.span_order: List[int] = []  # segment index per span, in order
        self.tail_ops = []           # pre-segment bwd + optimizer ops


def _fallback(reason):
    warnings.warn(
        f"RecomputeOptimizer checkpoints not lowerable onto "
        f"jax.checkpoint segments ({reason}); executing without "
        f"rematerialization (same numerics, more memory)", stacklevel=3)
    return None


def build_plan(cb, ckpt_names) -> Optional[RematPlan]:
    ops = cb.ops
    producer = {}
    for i, op in enumerate(ops):
        for n in op.output_arg_names:
            producer.setdefault(n, i)
    missing = [c for c in ckpt_names if c not in producer]
    if missing:
        return _fallback(f"checkpoint vars {missing} not produced")
    cks = sorted(set(ckpt_names), key=lambda c: producer[c])
    # find where the forward ends: the loss-grad seed fill_constant is
    # the first op whose outputs are all @GRAD names
    fwd_end = len(ops)
    for i, op in enumerate(ops):
        outs = op.output_arg_names
        if outs and all("@GRAD" in n for n in outs):
            fwd_end = i
            break
    bounds = [producer[c] + 1 for c in cks]
    if bounds[-1] > fwd_end:
        return _fallback("a checkpoint is produced by a backward op")

    plan = RematPlan()
    # segments live BETWEEN checkpoints: the region up to the first
    # checkpoint stays un-remat'ed (its inputs are the feeds; storing
    # them is free), matching the reference's use of checkpoints as
    # segment boundaries
    plan.pre_ops = ops[:bounds[0]]
    seg_ranges = [(bounds[i], bounds[i + 1])
                  for i in range(len(bounds) - 1)]
    if bounds[-1] < fwd_end:
        seg_ranges.append((bounds[-1], fwd_end))
    if not seg_ranges:
        return _fallback("need at least one segment after a checkpoint")
    rest = ops[fwd_end:]

    from .executor import _op_needs_rng

    def _op_uses_rng(op):
        """rng-REGISTERED is not rng-USING: an attention/dropout op with
        rate 0 (or is_test) draws nothing, so remat replay is exact."""
        if not _op_needs_rng(op.type):
            return False
        if op.attrs.get("is_test"):
            return False
        rate_keys = [k for k in op.attrs
                     if k in ("dropout_rate", "dropout_prob")]
        if rate_keys:
            return max(float(op.attrs[k] or 0.0) for k in rate_keys) > 0.0
        return True  # unconditional generator (uniform_random, ...)

    # writeback names that must survive even if no forward op reads
    # them: mutable state + persistable outputs (batch_norm running
    # stats, counters) — a segment-local write would otherwise be
    # silently dropped and the old value written back every step
    writeback = set(cb.mut_state) | set(cb.extra_writeback)
    fwd_reads: Dict[int, set] = {}
    for i, op in enumerate(ops[:fwd_end]):
        fwd_reads[i] = set(op.input_arg_names)
    for lo, hi in seg_ranges:
        seg = Segment()
        seg.ops = ops[lo:hi]
        if not seg.ops:
            return _fallback("empty checkpoint segment")
        for op in seg.ops:
            if op.attrs.get("sub_block") is not None:
                return _fallback("control flow inside a segment")
            if _op_uses_rng(op):
                # segment-local rng indices would collide across
                # segments and diverge from the fused run's keys
                return _fallback(
                    f"rng op '{op.type}' inside a segment")
        written = set()
        for op in seg.ops:
            for n in op.input_arg_names:
                if n not in written and n not in seg.ins:
                    seg.ins.append(n)
            written.update(op.output_arg_names)
        # outputs = the segment BOUNDARY: vars consumed by other FORWARD
        # ops, fetched, or state/persistable writebacks. Backward reads
        # of internals don't count — the segment's grad ops are replaced
        # by the vjp, which recomputes those values (that IS the
        # rematerialization); a non-replaced rest op reading an internal
        # is checked at the end.
        outside = set(cb.fetch_names) | writeback
        for i in range(fwd_end):
            if lo <= i < hi:
                continue
            outside |= fwd_reads[i]
        seg.outs = [n for n in written if n in outside]
        if not seg.outs:
            return _fallback("segment writes nothing consumed outside")
        plan.segments.append(seg)

    # ---- classify the backward spans ------------------------------------
    def grad_names_of(names):
        g = set()
        for v in names:
            g.add(grad_var_name(v))
        return g

    span_sets = []
    for seg in plan.segments:
        written = set()
        for op in seg.ops:
            written.update(op.output_arg_names)
        # a segment's span produces grads of its INTERNALS and INPUTS;
        # its outputs' grads come from the CONSUMER segment's span (or
        # the loss head), so they are not owned here
        owned = (written - set(seg.outs)) | set(seg.ins)
        span_sets.append(grad_names_of(owned))

    grad_owner: Dict[str, int] = {}
    for k, gset in enumerate(span_sets):
        for g in gset:
            if g in grad_owner and grad_owner[g] != k:
                return _fallback(
                    f"grad name '{g}' claimed by two segments")
            grad_owner[g] = k

    def owner_of(op):
        hits = set()
        for n in op.output_arg_names:
            # fan-in renames look like '<primal>@GRAD@RENAME@...' —
            # normalize to the base grad name for the dict lookup
            base = n
            i = n.find("@GRAD")
            if i >= 0:
                base = n[:i + 5]
            k = grad_owner.get(base)
            if k is not None:
                hits.add(k)
        return hits

    idxs: Dict[int, List[int]] = {k: [] for k in range(len(plan.segments))}
    for i, op in enumerate(rest):
        hits = owner_of(op)
        if len(hits) > 1:
            return _fallback(
                f"grad op '{op.type}' mixes segments {sorted(hits)} "
                f"(shared params across segments)")
        if hits:
            idxs[hits.pop()].append(i)
    live = [k for k in idxs if idxs[k]]
    if not live:
        return _fallback("no segment gradient ops found")
    # spans must be contiguous and in reverse segment order
    ordered = sorted(live, key=lambda k: idxs[k][0])
    if ordered != sorted(live, reverse=True):
        return _fallback("backward spans not in reverse segment order")
    marks = []
    for k in ordered:
        lo, hi = min(idxs[k]), max(idxs[k])
        if any(i not in idxs[k] for i in range(lo, hi + 1)):
            return _fallback(f"segment {k} backward span not contiguous")
        marks.append((k, lo, hi))
    # a segment input's grad must come ONLY from its own span: every
    # grad-of-input write outside the span falls back (fan-in)
    plan.rest_head = rest[:marks[0][1]]
    plan.spans = [None] * len(plan.segments)
    plan.between = []
    cur = None
    for j, (k, lo, hi) in enumerate(marks):
        plan.spans[k] = rest[lo:hi + 1]
        nxt_lo = marks[j + 1][1] if j + 1 < len(marks) else None
        seg_after = rest[hi + 1:nxt_lo] if nxt_lo is not None \
            else rest[hi + 1:]
        plan.between.append(seg_after)
    plan.span_order = [k for k, _, _ in marks]
    plan.tail_ops = plan.between.pop() if plan.between else []
    # every rest op that SURVIVES (not in a replaced span) must not read
    # a segment internal — those values are never materialized in env
    internals = set()
    for seg in plan.segments:
        w = set()
        for op in seg.ops:
            w.update(op.output_arg_names)
        internals |= (w - set(seg.outs))
    replaced = {id(op) for span in plan.spans if span for op in span}
    for op in rest:
        if id(op) in replaced:
            continue
        bad = internals & set(op.input_arg_names)
        if bad:
            return _fallback(
                f"op '{op.type}' outside the replaced spans reads "
                f"segment internals {sorted(bad)[:3]}")
    return plan


def exec_plan(cb, plan: RematPlan, env: Dict[str, Any], lod_env, rng):
    """One rematerialized step into ``env`` (called inside jit)."""
    cb._exec_ops(plan.pre_ops, env, lod_env, rng)

    vjps = []
    for seg in plan.segments:
        ins = [env[n] for n in seg.ins]

        def seg_fn(vals, _seg=seg):
            e = {n: v for n, v in zip(_seg.ins, vals)}
            cb._exec_ops(_seg.ops, e, dict(lod_env), rng)
            return tuple(e[n] for n in _seg.outs)

        wrapped = jax.checkpoint(seg_fn)
        outs, vjp_fn = jax.vjp(wrapped, ins)
        for n, v in zip(seg.outs, outs):
            env[n] = v
        vjps.append(vjp_fn)

    cb._exec_ops(plan.rest_head, env, lod_env, rng)

    import numpy as _np
    for j, k in enumerate(plan.span_order):
        seg = plan.segments[k]
        cots = []
        for n in seg.outs:
            out_val = env[n]
            if not jnp.issubdtype(out_val.dtype, jnp.inexact):
                # integer/bool boundary: vjp wants a float0 tangent
                cots.append(_np.zeros(out_val.shape, jax.dtypes.float0))
                continue
            g = env.get(grad_var_name(n))
            if g is None:
                cots.append(jnp.zeros_like(out_val))
            else:
                cots.append(g.astype(out_val.dtype)
                            if g.dtype != out_val.dtype else g)
        (d_ins,) = vjps[k](tuple(cots))
        for n, g in zip(seg.ins, d_ins):
            if g is not None:
                env[grad_var_name(n)] = g
        after = plan.between[j] if j < len(plan.between) else []
        cb._exec_ops(after, env, lod_env, rng)

    cb._exec_ops(plan.tail_ops, env, lod_env, rng)
