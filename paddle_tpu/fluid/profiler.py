"""Profiler (reference: python/paddle/fluid/profiler.py over
platform/profiler.h — RecordEvent:124 RAII spans nested per op,
EnableProfiler/DisableProfiler:206 with sorted summary tables
(profiler_helper.h), CUPTI DeviceTracer → chrome://tracing via
tools/timeline.py).

TPU layering:
  * host spans — RecordEvent stack collected here; the executor wraps each
    eager op and each compiled-step dispatch (operator.cc:948-977 hook
    points). stop_profiler prints the reference-style sorted table and
    writes a chrome://tracing JSON that tools/timeline.py merges/views.
  * device timeline — jax.profiler XPlane trace (TensorBoard/Perfetto),
    the DeviceTracer/CUPTI replacement; enabled when state includes the
    accelerator.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import jax

from . import core
from . import telemetry

__all__ = ["cuda_profiler", "reset_profiler", "profiler", "start_profiler",
           "stop_profiler", "record_event", "RecordEvent", "is_profiling",
           "record_span", "record_instant", "snapshot_events",
           "concurrent_seconds", "dropped_events"]


class _Event:
    __slots__ = ("name", "start", "end", "tid", "cat", "args",
                 "trace_id", "span_id", "parent_id")

    def __init__(self, name, start, end, tid, cat="host", args=None,
                 trace_id=None, span_id=None, parent_id=None):
        self.name = name
        self.start = start
        self.end = end
        self.tid = tid
        self.cat = cat
        self.args = args  # chrome-trace "args" payload (e.g. rpc bytes)
        # trace correlation (telemetry.trace_scope): stamped from the
        # recording thread's installed context, None outside any trace
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id


def _ring(maxlen_hint: Optional[int] = None) -> deque:
    """FLAGS_profiler_max_events-bounded event store: beyond the bound
    the OLDEST events drop (counted) instead of growing the host heap
    for a long run's lifetime. The bound is read at ring creation —
    start_profiler / reset_profiler — not per append."""
    n = maxlen_hint if maxlen_hint is not None else int(
        core.globals_["FLAGS_profiler_max_events"])
    return deque(maxlen=max(1, n))


class _ProfilerState:
    def __init__(self):
        self.enabled = False
        self.state = "All"
        self.events: deque = _ring(1024)
        self.dropped = 0
        self.lock = threading.Lock()
        self.t0 = 0.0
        self.trace_dir: Optional[str] = None
        self.device_tracing = False
        self.depth = 0  # nested profiler()/cuda_profiler() contexts


_prof = _ProfilerState()


def is_profiling() -> bool:
    """True when spans should be recorded: an explicit profiler session
    is on OR FLAGS_trace_dir shard streaming is active (the cluster-
    timeline mode records without start_profiler)."""
    return _prof.enabled or telemetry.shard_active()


def is_session() -> bool:
    """True ONLY during an explicit start_profiler() session — the gate
    for measurement-mode side effects (executor block_until_ready,
    numeric-guard flag readbacks). FLAGS_trace_dir shard streaming
    records spans WITHOUT them: a shard-only step span measures
    dispatch, not device completion, so always-on cluster tracing never
    re-adds the per-step host syncs PR 5 engineered away
    (docs/OBSERVABILITY.md "1-core caveats")."""
    return _prof.enabled


def dropped_events() -> int:
    """Events dropped by the FLAGS_profiler_max_events ring since the
    last start/reset."""
    with _prof.lock:
        return _prof.dropped


def start_profiler(state="All", tracer_option="Default",
                   trace_dir="/tmp/paddle_tpu_profile"):
    """reference profiler.py start_profiler / EnableProfiler. ``state``:
    'CPU' = host spans only; 'GPU'/'All' also starts the device (XPlane)
    trace."""
    if _prof.enabled:
        _prof.depth += 1  # nested enable: inner stop becomes a no-op pair
        return
    _prof.depth = 1
    with _prof.lock:
        _prof.events = _ring()
        _prof.dropped = 0
    _prof.enabled = True
    _prof.state = state
    _prof.t0 = time.perf_counter()
    _prof.device_tracing = state in ("GPU", "All")
    if _prof.device_tracing:
        _prof.trace_dir = trace_dir
        try:
            jax.profiler.start_trace(trace_dir)
        except RuntimeError:
            _prof.device_tracing = False


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: str = "/tmp/profile"):
    """Print the sorted summary table (reference profiler_helper.h
    PrintProfiler) and write a chrome://tracing JSON to ``profile_path``
    (consumed by tools/timeline.py)."""
    if not _prof.enabled:
        return
    _prof.depth -= 1
    if _prof.depth > 0:  # inner context of a nested session: keep going
        return
    _prof.enabled = False
    if _prof.device_tracing:
        jax.profiler.stop_trace()
        print(f"[profiler] device XPlane trace in {_prof.trace_dir} "
              f"(TensorBoard / Perfetto)")
    with _prof.lock:
        events = list(_prof.events)
        dropped = _prof.dropped
    if dropped:
        print(f"[profiler] {dropped} oldest event(s) dropped by the "
              f"FLAGS_profiler_max_events ring "
              f"(bound {_prof.events.maxlen})")
    _summary(events, sorted_key)
    if profile_path:
        _write_chrome_trace(events, profile_path)
        print(f"[profiler] host timeline written to {profile_path} "
              f"(tools/timeline.py or chrome://tracing)")


def reset_profiler():
    with _prof.lock:
        _prof.events = _ring()
        _prof.dropped = 0
        _prof.t0 = time.perf_counter()


def _record(name: str, start: float, end: float, cat: str = "host",
            args=None):
    tctx = telemetry.current_trace()
    tid = threading.get_ident()
    if _prof.enabled:
        if tctx is None:
            ev = _Event(name, start, end, tid, cat, args)
        else:
            ev = _Event(name, start, end, tid, cat, args,
                        tctx.trace_id, tctx.span_id, tctx.parent_id)
        with _prof.lock:
            if len(_prof.events) == _prof.events.maxlen:
                _prof.dropped += 1
            _prof.events.append(ev)
    # cluster-timeline shard (FLAGS_trace_dir): every recorded span also
    # streams to the process's chrome-trace shard — no-op when off
    telemetry.shard_record(name, start, end, tid, cat, args, tctx)


def record_span(name: str, start: float, end: float, cat: str = "host",
                args=None) -> None:
    """Record an already-timed span (perf_counter endpoints). No-op when
    profiling is off. Used by layers that time work themselves — the PS
    RPC client attaches byte/retry counts as chrome-trace args here."""
    if is_profiling():
        _record(name, start, end, cat, args)


def record_instant(name: str, cat: str = "host", args=None) -> None:
    """Zero-duration marker event. No-op when profiling is off. The
    numeric fault plane emits its trip/rollback markers here under
    cat='health' (args carry the step, the offending segment, and the
    action taken) so they land beside the cat='segment'/'window'/'rpc'
    spans in the chrome trace."""
    if is_profiling():
        t = time.perf_counter()
        _record(name, t, t, cat, args)


def snapshot_events():
    """Thread-safe copy of the recorded host events as plain dicts
    (name/start/end/tid/cat/args + trace correlation ids) — for tests
    and bench lanes that compute evidence from a live profile (e.g. the
    async-overlap concurrency check) without stopping the profiler."""
    with _prof.lock:
        return [{"name": e.name, "start": e.start, "end": e.end,
                 "tid": e.tid, "cat": e.cat, "args": e.args,
                 "trace_id": e.trace_id, "span_id": e.span_id,
                 "parent_id": e.parent_id}
                for e in _prof.events]


def _merge_intervals(spans):
    out = []
    for s, e in sorted(spans):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out


def concurrent_seconds(cat_a: str, cat_b: str, events=None) -> float:
    """Wall seconds during which a ``cat_a`` span overlaps IN TIME with
    a ``cat_b`` span recorded on a DIFFERENT thread — the async-overlap
    plane's evidence metric (docs/PS_DATA_PLANE.md "Async overlap"):
    cat='comm' spans (round pipeline / prefetch threads) concurrent
    with cat='segment'/'window' step spans on the main thread prove the
    wire ran behind the compiled step instead of taking turns with
    it. Both span sets are union-merged first so nesting never double
    counts."""
    events = snapshot_events() if events is None else events
    total = 0.0
    a_tids = {e["tid"] for e in events if e["cat"] == cat_a}
    for tid in a_tids:
        a = _merge_intervals([(e["start"], e["end"]) for e in events
                              if e["cat"] == cat_a and e["tid"] == tid])
        b = _merge_intervals([(e["start"], e["end"]) for e in events
                              if e["cat"] == cat_b and e["tid"] != tid])
        i = j = 0
        while i < len(a) and j < len(b):
            s = max(a[i][0], b[j][0])
            e = min(a[i][1], b[j][1])
            if e > s:
                total += e - s
            if a[i][1] <= b[j][1]:
                i += 1
            else:
                j += 1
    return total


class RecordEvent:
    """RAII span (reference platform/profiler.h:124). Usable as a context
    manager or decorator; no-op when profiling is off. ``cat`` groups
    spans in the chrome trace — the segmented executor emits its
    per-segment compile/exec and island spans under cat='segment' so the
    compiled/interpreted partition of a step is visible at a glance,
    multi-step windows emit one cat='window' span per dispatched window
    (window[K]:realdata | :broadcast | :fallback — the one-dispatch-per-
    window evidence tests/test_window_executor.py counts), the serving
    plane emits cat='serve' queue-wait/exec spans whose ``args`` carry
    bucket + batch-size chrome-trace payloads plus serve:shed /
    serve:deadline_expired / serve:degraded instants from the ingress
    overload plane (record_instant — args name the drop site:
    admission | codel | rate_gate; docs/SERVING.md "Ingress &
    overload"), and the
    async overlap plane emits cat='comm' spans from its background
    threads (ps_round[i] rounds, sparse_push tasks, prefetch[table]
    fetches, plus main-thread round:stall[pipe_full] backpressure) whose
    concurrency with the step spans ``concurrent_seconds`` measures."""

    def __init__(self, name: str, cat: str = "host", args=None):
        self.name = name
        self.cat = cat
        self.args = args
        self._start = 0.0

    def __enter__(self):
        if _prof.enabled:
            self._start = time.perf_counter()
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        elif telemetry.shard_active():
            # FLAGS_trace_dir shard-only mode: record the span without
            # the jax device-trace annotation (no XPlane session is on)
            self._start = time.perf_counter()
            self._ann = None
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        # gate on the per-span state, not the global flag: a stop_profiler
        # landing mid-span must not leak the entered TraceAnnotation
        if self._start:
            if self._ann is not None:
                self._ann.__exit__(exc_type, exc_val, exc_tb)
            _record(self.name, self._start, time.perf_counter(), self.cat,
                    self.args)
            self._start = 0.0
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(self.name):
                return fn(*args, **kwargs)
        return wrapper


@contextlib.contextmanager
def record_event(name: str):
    with RecordEvent(name):
        yield


# ---------------------------------------------------------------- reports
_SORT_KEYS = {"total", "calls", "max", "min", "ave", None}


def _summary(events: List[_Event], sorted_key: Optional[str]):
    if sorted_key not in _SORT_KEYS:
        raise ValueError(f"sorted_key must be one of {_SORT_KEYS}")
    if not events:
        print("[profiler] no host events recorded")
        return
    agg: Dict[str, List[float]] = {}
    for e in events:
        agg.setdefault(e.name, []).append((e.end - e.start) * 1000.0)
    total_all = sum(sum(v) for v in agg.values())
    rows = []
    for name, vals in agg.items():
        tot = sum(vals)
        rows.append((name, len(vals), tot, tot / len(vals), max(vals),
                     min(vals), tot / total_all if total_all else 0.0))
    key_idx = {"calls": 1, "total": 2, "ave": 3, "max": 4, "min": 5,
               None: 2}[sorted_key]
    rows.sort(key=lambda r: -r[key_idx])
    hdr = (f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
           f"{'Max(ms)':>10}{'Min(ms)':>10}{'Ratio':>8}")
    print("-------------------------     Profiling Report     "
          "-------------------------")
    print(hdr)
    for name, calls, tot, ave, mx, mn, ratio in rows:
        print(f"{name[:39]:<40}{calls:>8}{tot:>12.4f}{ave:>10.4f}"
              f"{mx:>10.4f}{mn:>10.4f}{ratio:>8.2%}")


def _write_chrome_trace(events: List[_Event], path: str):
    """chrome://tracing JSON (the format tools/timeline.py emits in the
    reference)."""
    trace = {"traceEvents": [], "displayTimeUnit": "ms"}
    for e in events:
        ev = {
            "name": e.name, "ph": "X", "pid": os.getpid(), "tid": e.tid,
            "ts": (e.start - _prof.t0) * 1e6,
            "dur": (e.end - e.start) * 1e6, "cat": e.cat}
        args = dict(e.args) if e.args else {}
        if e.trace_id is not None:
            args["trace_id"] = e.trace_id
            args["span_id"] = e.span_id
            if e.parent_id:
                args["parent_id"] = e.parent_id
        if args:
            ev["args"] = args
        trace["traceEvents"].append(ev)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option="Default"):
    """reference profiler.py profiler context manager."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    # accelerator profiler alias — same device trace
    with profiler(state="All", profile_path=output_file or "/tmp/profile"):
        yield
