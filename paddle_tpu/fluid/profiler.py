"""Profiler (reference: python/paddle/fluid/profiler.py over
platform/profiler.h RecordEvent/CUPTI DeviceTracer).

TPU equivalent: jax.profiler — XPlane traces viewable in TensorBoard /
Perfetto replace the chrome://tracing timeline (reference tools/timeline.py).
API shape preserved: profiler(...)/start_profiler/stop_profiler context."""
from __future__ import annotations

import contextlib
import time

import jax

__all__ = ["cuda_profiler", "reset_profiler", "profiler", "start_profiler",
           "stop_profiler"]

_trace_dir = None


def start_profiler(state="All", tracer_option="Default",
                   trace_dir="/tmp/paddle_tpu_profile"):
    global _trace_dir
    _trace_dir = trace_dir
    jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    jax.profiler.stop_trace()
    if _trace_dir:
        print(f"[profiler] XPlane trace written to {_trace_dir} "
              f"(view with TensorBoard)")


def reset_profiler():
    pass


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option="Default"):
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    # accelerator profiler alias — same jax trace
    with profiler():
        yield


@contextlib.contextmanager
def record_event(name: str):
    """RecordEvent RAII span (reference platform/profiler.h:124)."""
    with jax.profiler.TraceAnnotation(name):
        yield
