"""DataLoader (reference: python/paddle/fluid/reader.py:100 —
DataLoader.from_generator/from_dataset, GeneratorLoader).

TPU design: the async C++ BufferedReader/py_reader double-buffering of the
reference is replaced by a host-side prefetch thread; device transfer
overlaps with compute because jax dispatch is async. set_sample_generator /
set_sample_list_generator / set_batch_generator mirror the reference API."""
from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional

import numpy as np

from . import core
from .data_feeder import DataFeeder
from .framework import Variable

__all__ = ["DataLoader", "PyReader"]


class _GeneratorLoader:
    def __init__(self, feed_list, capacity=16, iterable=True,
                 return_list=False, use_multiprocess=False):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._use_multiprocess = use_multiprocess
        self._batch_fn: Optional[Callable] = None
        self._places = None

    # -- reference API -----------------------------------------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batch_reader():
            batch = []
            for sample in reader():
                batch.append(sample if isinstance(sample, (list, tuple))
                             else (sample,))
                if len(batch) == batch_size:
                    yield batch
                    batch = []
            if batch and not drop_last:
                yield batch
        return self.set_sample_list_generator(batch_reader, places)

    def set_sample_list_generator(self, reader, places=None):
        places = _first_place(places)
        feeder = DataFeeder(self._feed_list, places)

        def fn():
            for sample_list in reader():
                yield feeder.feed(sample_list)
        self._batch_fn = fn
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        places = _first_place(places)
        names = [v.name if isinstance(v, Variable) else v
                 for v in self._feed_list]

        def fn():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    if not isinstance(batch, (list, tuple)):
                        batch = (batch,)
                    yield {n: b for n, b in zip(names, batch)}
        self._batch_fn = fn
        self._places = places
        return self

    def __iter__(self):
        assert self._batch_fn is not None, "no generator set"
        if self._use_multiprocess:
            yield from self._iter_multiprocess()
            return
        if self._capacity <= 1:
            yield from self._batch_fn()
            return
        q: "queue.Queue" = queue.Queue(self._capacity)
        DONE = object()

        def producer():
            try:
                for item in self._batch_fn():
                    q.put(item)
            finally:
                q.put(DONE)
        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is DONE:
                break
            yield item

    def _iter_multiprocess(self):
        """Producer process + shared-memory batch transport (reference:
        reader.py:684-760 multiprocess GeneratorLoader whose LoDTensors ride
        mmap allocations — memory/allocation/mmap_allocator.cc; here each
        array crosses via multiprocessing.shared_memory and only metadata is
        pickled). The child is a daemon: an abandoned iterator or a parent
        crash cannot leak it."""
        import multiprocessing as mp
        from multiprocessing import shared_memory

        ctx = mp.get_context("fork")  # the generator closure must carry over
        meta_q = ctx.Queue(self._capacity)
        batch_fn = self._batch_fn

        def producer():
            import signal
            pending = []  # names created but whose meta hasn't been sent

            def _cleanup_pending(*_):
                # terminate() while blocked in meta_q.put: the consumer
                # will never see these names, unlink them ourselves
                for shm_name in pending:
                    try:
                        s = shared_memory.SharedMemory(name=shm_name)
                        s.close()
                        s.unlink()
                    except FileNotFoundError:
                        pass
                raise SystemExit(0)

            signal.signal(signal.SIGTERM, _cleanup_pending)
            try:
                for item in batch_fn():
                    meta = {}
                    for name, arr in item.items():
                        a = np.ascontiguousarray(arr)
                        shm = shared_memory.SharedMemory(create=True,
                                                         size=max(1, a.nbytes))
                        shm.buf[:a.nbytes] = a.tobytes()
                        meta[name] = (shm.name, a.shape, a.dtype.str)
                        pending.append(shm.name)
                        shm.close()
                    meta_q.put(("batch", meta))
                    pending.clear()  # consumer owns them now
                meta_q.put(("done", None))
            except Exception as e:  # surface the generator's error
                meta_q.put(("error", repr(e)))

        proc = ctx.Process(target=producer, daemon=True)
        core.start_forked_quietly([proc])

        def _unlink_meta(meta):
            for shm_name, _, _ in meta.values():
                try:
                    s = shared_memory.SharedMemory(name=shm_name)
                    s.close()
                    s.unlink()
                except FileNotFoundError:
                    pass

        try:
            while True:
                try:
                    # bounded get + liveness check: a killed child must not
                    # hang the consumer forever
                    kind, meta = meta_q.get(timeout=5.0)
                except queue.Empty:
                    if not proc.is_alive():
                        raise RuntimeError(
                            "multiprocess DataLoader worker died without "
                            f"posting 'done' (exitcode={proc.exitcode})")
                    continue
                if kind == "done":
                    break
                if kind == "error":
                    raise RuntimeError(
                        f"multiprocess DataLoader worker failed: {meta}")
                batch = {}
                for name, (shm_name, shape, dtype) in meta.items():
                    shm = shared_memory.SharedMemory(name=shm_name)
                    n = int(np.prod(shape)) if shape else 1
                    arr = np.frombuffer(
                        shm.buf, dtype=np.dtype(dtype),
                        count=n).reshape(shape).copy()
                    shm.close()
                    shm.unlink()
                    batch[name] = arr
                yield batch
        finally:
            proc.terminate()
            proc.join(timeout=5.0)
            # drain the queue unlinking any segments the consumer never
            # touched (early break / producer error), so /dev/shm doesn't
            # accumulate leaked blocks
            while True:
                try:
                    kind, meta = meta_q.get_nowait()
                except queue.Empty:
                    break
                if kind == "batch":
                    _unlink_meta(meta)

    def __call__(self):
        return iter(self)

    # non-iterable (start/reset) mode used with py_reader-style loops
    def start(self):
        self._it = iter(self)

    def reset(self):
        self._it = None


def _first_place(places):
    if places is None:
        return core.TPUPlace(0) if core.is_compiled_with_tpu() \
            else core.CPUPlace()
    if isinstance(places, (list, tuple)):
        return places[0]
    return places


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=16, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        return _GeneratorLoader(feed_list, capacity, iterable, return_list,
                                use_multiprocess=use_multiprocess)

    @staticmethod
    def from_dataset(dataset, places, drop_last=True):
        from .dataset_loader import DatasetLoader
        return DatasetLoader(dataset, places, drop_last)


class PyReader(_GeneratorLoader):
    """reference reader.py PyReader — same loader, py_reader-era name."""

    def __init__(self, feed_list=None, capacity=16, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list, capacity, iterable, return_list)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)
