"""DataLoader (reference: python/paddle/fluid/reader.py:100 —
DataLoader.from_generator/from_dataset, GeneratorLoader).

TPU design: the async C++ BufferedReader/py_reader double-buffering of the
reference is replaced by a host-side prefetch thread; device transfer
overlaps with compute because jax dispatch is async. set_sample_generator /
set_sample_list_generator / set_batch_generator mirror the reference API.

``window(k)`` goes one further than the reference's double buffering: a
background stage stacks K host batches into ONE [K, batch, ...] feed dict
and device_puts window i+1 while window i computes — the executor consumes
it as a single dispatched lax.scan over K *distinct* batches
(``Executor.run(n_steps=K)``; docs/INPUT_PIPELINE.md)."""
from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional

import numpy as np

from . import core
from .data_feeder import DataFeeder
from .framework import Variable

__all__ = ["DataLoader", "PyReader", "WindowBatch"]


class WindowBatch(dict):
    """K stacked batches as one feed dict: every value carries a leading
    [k, ...] window dim — feed it straight into ``Executor.run`` with
    ``n_steps=k``. ``n_valid`` ≤ k counts the real (unpadded) steps;
    ``mask`` is a [k] float32 0/1 vector. A padded tail window
    (``drop_last=False``) repeats its final real batch, and those padded
    steps DO execute — weight per-step stacked fetches by ``mask`` (and
    be aware padded steps also apply optimizer updates; drop the tail
    when exact epoch semantics matter)."""

    def __init__(self, data, k: int, n_valid: int):
        super().__init__(data)
        self.k = int(k)
        self.n_valid = int(n_valid)

    @property
    def mask(self) -> np.ndarray:
        m = np.zeros(self.k, np.float32)
        m[:self.n_valid] = 1.0
        return m


def _iter_through_queue(src_iter, capacity: int, transform=None):
    """Bridge ``src_iter`` through a bounded queue filled by a daemon
    thread (the prefetch shape every loader stage here uses). The
    producer applies ``transform`` to each item (e.g. the device upload)
    so that work overlaps the consumer's compute; generator errors
    re-raise in the consumer. When the consumer goes away early (break,
    exception, GC) the ``finally`` signals the producer, which abandons
    its blocked put instead of pinning ``capacity`` buffered items for
    the process lifetime."""
    q: "queue.Queue" = queue.Queue(max(1, capacity))
    DONE, ERR = object(), object()
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in src_iter:
                if transform is not None:
                    item = transform(item)
                if not put(item):
                    return  # consumer gone
            put(DONE)
        except BaseException as e:  # surface in the consumer
            put((ERR, e))

    threading.Thread(target=producer, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is DONE:
                return
            if isinstance(item, tuple) and len(item) == 2 \
                    and item[0] is ERR:
                raise item[1]
            yield item
    finally:
        stop.set()


def _stack_window(batches, k: int, n_valid: int) -> WindowBatch:
    """Stack a list of feed dicts along a new leading window dim. LoD
    batches are refused (one LoD cannot describe K stacked batches) and
    ragged batch shapes get a pointed error instead of np.stack's."""
    first = batches[0]
    out = {}
    for name in first:
        parts = []
        for b in batches:
            v = b[name]
            if isinstance(v, core.LoDTensor):
                if v.lod():
                    raise ValueError(
                        f"window(): batch var '{name}' carries LoD — "
                        f"stacked windows need dense batches; keep LoD "
                        f"data on the per-step path")
                v = v.array
            parts.append(np.asarray(v))
        if any(p.shape != parts[0].shape for p in parts[1:]):
            raise ValueError(
                f"window(): ragged batch shapes for '{name}' "
                f"({sorted({p.shape for p in parts})}) — use a "
                f"fixed batch_size (drop_last=True upstream) so K "
                f"batches stack")
        out[name] = np.stack(parts)
    return WindowBatch(out, k, n_valid)


class _GeneratorLoader:
    def __init__(self, feed_list, capacity=16, iterable=True,
                 return_list=False, use_multiprocess=False,
                 drop_last=True, worker_timeout=None, join_timeout=None):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._use_multiprocess = use_multiprocess
        self._drop_last = drop_last
        # multiprocess liveness/teardown timeouts: kwarg wins, else the
        # FLAGS_dataloader_*_timeout globals (read at iteration time so
        # tests/flags can adjust after construction)
        self._worker_timeout = worker_timeout
        self._join_timeout = join_timeout
        self._batch_fn: Optional[Callable] = None
        self._places = None
        self._it = None     # non-iterable (start/next/reset) mode state
        self._mp_proc = None  # last multiprocess worker (observability)
        # epoch/position counters for checkpoint manifests
        # (state_dict/load_state_dict — docs/FAULT_TOLERANCE.md): epoch =
        # completed passes, position = batches yielded this epoch
        self._epoch = 0
        self._position = 0
        self._skip_next = 0

    # -- reference API -----------------------------------------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batch_reader():
            batch = []
            for sample in reader():
                batch.append(sample if isinstance(sample, (list, tuple))
                             else (sample,))
                if len(batch) == batch_size:
                    yield batch
                    batch = []
            if batch and not drop_last:
                yield batch
        return self.set_sample_list_generator(batch_reader, places)

    def set_sample_list_generator(self, reader, places=None):
        places = _first_place(places)
        feeder = DataFeeder(self._feed_list, places)

        def fn():
            for sample_list in reader():
                yield feeder.feed(sample_list)
        self._batch_fn = fn
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        places = _first_place(places)
        names = [v.name if isinstance(v, Variable) else v
                 for v in self._feed_list]

        def fn():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    if not isinstance(batch, (list, tuple)):
                        batch = (batch,)
                    yield {n: b for n, b in zip(names, batch)}
        self._batch_fn = fn
        self._places = places
        return self

    def __iter__(self):
        """Wraps the raw batch stream with epoch/position accounting.
        After ``load_state_dict`` the first ``position`` batches of the
        epoch are consumed WITHOUT being yielded (fast-forward): with a
        deterministic generator the resumed stream continues exactly
        where the checkpointed run was cut."""
        inner = self._iter_raw()
        skip, self._skip_next = self._skip_next, 0
        pos = 0
        for batch in inner:
            pos += 1
            if pos <= skip:
                continue
            self._position = pos
            yield batch
        self._epoch += 1
        self._position = 0

    def _iter_raw(self):
        assert self._batch_fn is not None, "no generator set"
        if self._use_multiprocess:
            yield from self._iter_multiprocess()
            return
        if self._capacity <= 1:
            yield from self._batch_fn()
            return
        # NOTE: the old inline thread swallowed generator errors
        # (finally: put(DONE)) and left an abandoned producer blocked on
        # put forever — the shared bridge fixes both
        yield from _iter_through_queue(self._batch_fn(), self._capacity)

    # -------------------------------------------------- checkpoint state
    def state_dict(self):
        """Input-stream position for a checkpoint manifest (picked up by
        Executor.set_auto_checkpoint(dataloader=...))."""
        return {"epoch": self._epoch, "position": self._position}

    def load_state_dict(self, state):
        """Restore counters from a manifest; the NEXT iteration of this
        loader fast-forwards ``position`` batches (they are generated
        and discarded, not yielded). Exactness requires the same
        deterministic generator the checkpointed run used."""
        self._epoch = int(state.get("epoch", 0))
        self._position = int(state.get("position", 0))
        self._skip_next = self._position

    def _iter_multiprocess(self):
        """Producer process + shared-memory batch transport (reference:
        reader.py:684-760 multiprocess GeneratorLoader whose LoDTensors ride
        mmap allocations — memory/allocation/mmap_allocator.cc; here each
        array crosses via multiprocessing.shared_memory and only metadata is
        pickled). The child is a daemon: an abandoned iterator or a parent
        crash cannot leak it."""
        import multiprocessing as mp
        from multiprocessing import shared_memory

        ctx = mp.get_context("fork")  # the generator closure must carry over
        meta_q = ctx.Queue(self._capacity)
        batch_fn = self._batch_fn

        def producer():
            import signal
            pending = []  # names created but whose meta hasn't been sent

            def _cleanup_pending(*_):
                # terminate() while blocked in meta_q.put: the consumer
                # will never see these names, unlink them ourselves
                for shm_name in pending:
                    try:
                        s = shared_memory.SharedMemory(name=shm_name)
                        s.close()
                        s.unlink()
                    except FileNotFoundError:
                        pass
                raise SystemExit(0)

            signal.signal(signal.SIGTERM, _cleanup_pending)
            try:
                for item in batch_fn():
                    meta = {}
                    for name, arr in item.items():
                        a = np.ascontiguousarray(arr)
                        shm = shared_memory.SharedMemory(create=True,
                                                         size=max(1, a.nbytes))
                        shm.buf[:a.nbytes] = a.tobytes()
                        meta[name] = (shm.name, a.shape, a.dtype.str)
                        pending.append(shm.name)
                        shm.close()
                    meta_q.put(("batch", meta))
                    pending.clear()  # consumer owns them now
                meta_q.put(("done", None))
            except Exception as e:  # surface the generator's error
                meta_q.put(("error", repr(e)))

        proc = ctx.Process(target=producer, daemon=True)
        core.start_forked_quietly([proc])
        self._mp_proc = proc  # observable for tests/debugging
        liveness = (self._worker_timeout if self._worker_timeout is not None
                    else float(core.globals_[
                        "FLAGS_dataloader_worker_timeout"]))
        join_t = (self._join_timeout if self._join_timeout is not None
                  else float(core.globals_["FLAGS_dataloader_join_timeout"]))

        def _unlink_meta(meta):
            for shm_name, _, _ in meta.values():
                try:
                    s = shared_memory.SharedMemory(name=shm_name)
                    s.close()
                    s.unlink()
                except FileNotFoundError:
                    pass

        try:
            while True:
                try:
                    # bounded get + liveness check: a killed child must not
                    # hang the consumer forever (FLAGS_dataloader_worker_
                    # timeout / worker_timeout= kwarg)
                    kind, meta = meta_q.get(timeout=liveness)
                except queue.Empty:
                    if not proc.is_alive():
                        raise RuntimeError(
                            "multiprocess DataLoader worker died without "
                            f"posting 'done' (exitcode={proc.exitcode})")
                    continue
                if kind == "done":
                    break
                if kind == "error":
                    raise RuntimeError(
                        f"multiprocess DataLoader worker failed: {meta}")
                batch = {}
                for name, (shm_name, shape, dtype) in meta.items():
                    shm = shared_memory.SharedMemory(name=shm_name)
                    n = int(np.prod(shape)) if shape else 1
                    arr = np.frombuffer(
                        shm.buf, dtype=np.dtype(dtype),
                        count=n).reshape(shape).copy()
                    shm.close()
                    shm.unlink()
                    batch[name] = arr
                yield batch
        finally:
            proc.terminate()
            proc.join(timeout=join_t)
            # drain the queue unlinking any segments the consumer never
            # touched (early break / producer error), so /dev/shm doesn't
            # accumulate leaked blocks
            while True:
                try:
                    kind, meta = meta_q.get_nowait()
                except queue.Empty:
                    break
                if kind == "batch":
                    _unlink_meta(meta)

    # ------------------------------------------------------------ windows
    def window(self, k: int, drop_last=None, prefetch_to_device=True,
               prefetch_depth=2):
        """Iterate WindowBatch dicts of K stacked batches (the real-data
        multi-step shape: ``exe.run(feed=w, n_steps=k)`` scans the K
        slices in ONE dispatch on the compiled path).

        ``drop_last`` (None → the loader's drop_last): True drops a
        ragged tail of < k batches; False pads the tail window to k by
        repeating the final batch and marks it via ``n_valid``/``mask``
        (pad-and-mask keeps the jit cache at ONE window shape — the TPU
        trade; the padded steps do execute).

        ``prefetch_to_device``: a background stage device_puts window
        i+1 while window i computes — jax dispatch is async, so the
        host→device transfer overlaps compute and the executor receives
        already-resident arrays it never re-uploads
        (``_as_lodtensor`` fast path). ``prefetch_depth`` bounds the
        in-flight windows (2 = classic double buffering)."""
        if k < 1:
            raise ValueError(f"window size must be >= 1, got {k}")
        if drop_last is None:
            drop_last = self._drop_last

        def assemble():
            buf = []
            for batch in self:
                buf.append(batch)
                if len(buf) == k:
                    yield _stack_window(buf, k, k)
                    buf = []
            if buf and not drop_last:
                n = len(buf)
                buf = buf + [buf[-1]] * (k - n)
                yield _stack_window(buf, k, n)

        if not prefetch_to_device:
            return assemble()
        return _iter_through_queue(assemble(), prefetch_depth,
                                   transform=self._upload_window)

    @staticmethod
    def _upload_window(w: WindowBatch) -> WindowBatch:
        """Device-upload stage run on the prefetch thread: issues the
        (async) host→device transfer for the NEXT window while the
        consumer computes on the current one. _to_device_array applies
        the device int policy (int64 → int32) exactly like the
        executor's feed path would."""
        for name in list(w):
            w[name] = core._to_device_array(w[name])
        return w

    def __call__(self):
        return iter(self)

    # ------------------- non-iterable (start/next/reset) py_reader mode
    # Reference loop (reader.py PyReader, iterable=False):
    #     reader.start()
    #     while True:
    #         try:    exe.run(feed=reader.next(), ...)
    #         except fluid.core.EOFException:
    #             reader.reset(); break
    # (The reference feeds through in-program read ops; here next()
    # hands the feed dict to exe.run explicitly.)
    def start(self):
        if self._iterable:
            raise RuntimeError(
                "start() is the non-iterable protocol — construct the "
                "loader with iterable=False, or just iterate it")
        if self._it is not None:
            raise RuntimeError(
                "DataLoader already started; call reset() before "
                "starting the next epoch")
        self._it = iter(self)

    def next(self):
        """Next feed dict; raises core.EOFException when the epoch is
        drained (reset() then start() rearms — iter(self) re-invokes the
        generator factory, so epochs restart cleanly)."""
        if self._it is None:
            raise RuntimeError("DataLoader not started — call start()")
        try:
            return next(self._it)
        except StopIteration:
            raise core.EOFException(
                "DataLoader drained — call reset() (and start() for the "
                "next epoch)") from None

    next_batch = next  # py_reader-era alias

    def reset(self):
        self._it = None


def _first_place(places):
    if places is None:
        return core.TPUPlace(0) if core.is_compiled_with_tpu() \
            else core.CPUPlace()
    if isinstance(places, (list, tuple)):
        return places[0]
    return places


class _StreamLoader(_GeneratorLoader):
    """Unbounded streaming front end (PSLib continuous online learning —
    docs/INPUT_PIPELINE.md "Streaming reader"): no epochs, an event
    stream windows straight onto the PR 2 window substrate, and the
    checkpoint state is ONE number — the exact event offset the trainer
    has consumed.

    The source is seekable by contract: ``set_event_source(fn)`` takes
    ``fn(offset) -> iterator`` yielding per-event samples starting at
    event #offset. Resume therefore SEEKS instead of the epoch loader's
    consume-and-discard fast-forward: ``load_state_dict`` stores the
    offset and the next iteration asks the source for exactly that
    position, so a SIGKILL'd trainer replays bit-identical windows
    against an uninterrupted oracle (tests/test_streaming.py).

    Offset accounting is yield-granular: ``_offset`` advances when a
    batch/window is handed to the consumer — NOT when the prefetch
    stages read ahead — so a checkpoint taken between steps names
    precisely the events whose gradients are in the checkpointed
    weights; prefetched-but-unconsumed events are re-read after
    resume. Batches are always full (the stream never ends), so
    windows always stack cleanly."""

    def __init__(self, feed_list, batch_size, capacity=16):
        super().__init__(feed_list, capacity, iterable=True,
                         return_list=False, drop_last=True)
        self._batch_size = int(batch_size)
        self._source_fn = None
        self._offset = 0  # events consumed by yielded batches/windows

    # ------------------------------------------------------------ source
    def set_event_source(self, source_fn, places=None):
        """``source_fn(offset)`` must yield sample tuples (matching
        feed_list order) deterministically from event #offset."""
        self._source_fn = source_fn
        self._places = _first_place(places)
        return self

    def _raw_batches(self, start: int):
        assert self._source_fn is not None, "no event source set"
        feeder = DataFeeder(self._feed_list, self._places)

        def gen():
            buf = []
            for ev in self._source_fn(start):
                buf.append(ev if isinstance(ev, (list, tuple)) else (ev,))
                if len(buf) == self._batch_size:
                    yield feeder.feed(buf)
                    buf = []
        if self._capacity > 1:
            return _iter_through_queue(gen(), self._capacity)
        return gen()

    # --------------------------------------------------------- iteration
    def __iter__(self):
        start = self._offset
        n = 0
        for batch in self._raw_batches(start):
            n += 1
            # advance BEFORE yield (the epoch loader's position
            # convention): a checkpoint taken while the consumer holds
            # this batch includes its events
            self._offset = start + n * self._batch_size
            yield batch

    def window(self, k: int, drop_last=None, prefetch_to_device=True,
               prefetch_depth=2):
        """WindowBatch stream over the unbounded source; the offset
        advances window-at-a-time as each window reaches the consumer,
        so checkpoint/resume is window-aligned and bit-exact."""
        if k < 1:
            raise ValueError(f"window size must be >= 1, got {k}")
        start = self._offset
        per_window = k * self._batch_size

        def assemble():
            buf, wins = [], 0
            for batch in self._raw_batches(start):
                buf.append(batch)
                if len(buf) == k:
                    wins += 1
                    yield (start + wins * per_window,
                           _stack_window(buf, k, k))
                    buf = []

        src = assemble()
        if prefetch_to_device:
            src = _iter_through_queue(
                src, prefetch_depth,
                transform=lambda t: (t[0], self._upload_window(t[1])))

        def hand_out():
            for end, w in src:
                self._offset = end
                yield w
        return hand_out()

    # -------------------------------------------------- checkpoint state
    def state_dict(self):
        """Folded into the PR 3 checkpoint MANIFEST verbatim under the
        existing ``dataloader`` key (Executor.set_auto_checkpoint /
        resume_from thread it through unchanged — the contract is
        extended, not forked)."""
        return {"kind": "stream", "stream_offset": int(self._offset),
                "batch_size": self._batch_size}

    def load_state_dict(self, state):
        if state.get("kind") != "stream":
            # an epoch-loader manifest ({"epoch", "position"} — no
            # "kind" key) resumed into a stream loader is a config
            # bug — fail loudly, never silently restart at event 0
            raise ValueError(
                f"stream loader cannot resume from a {state.get('kind')!r}"
                f" dataloader state: {state}")
        self._offset = int(state.get("stream_offset", 0))

    @property
    def stream_offset(self) -> int:
        return self._offset


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=16, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True,
                       worker_timeout=None, join_timeout=None):
        return _GeneratorLoader(feed_list, capacity, iterable, return_list,
                                use_multiprocess=use_multiprocess,
                                drop_last=drop_last,
                                worker_timeout=worker_timeout,
                                join_timeout=join_timeout)

    @staticmethod
    def from_stream(feed_list=None, batch_size=1, capacity=16):
        """Unbounded streaming loader (see _StreamLoader): call
        ``set_event_source(fn)`` with a seekable ``fn(offset)`` event
        iterator, then iterate batches or ``window(k)`` stacks
        forever; checkpoint via state_dict/load_state_dict."""
        return _StreamLoader(feed_list, batch_size, capacity)

    @staticmethod
    def from_dataset(dataset, places, drop_last=True):
        from .dataset_loader import DatasetLoader
        return DatasetLoader(dataset, places, drop_last)


class PyReader(_GeneratorLoader):
    """reference reader.py PyReader — same loader, py_reader-era name."""

    def __init__(self, feed_list=None, capacity=16, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list, capacity, iterable, return_list)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)
