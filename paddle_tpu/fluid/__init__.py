"""paddle_tpu.fluid — the Fluid-compatible front end, TPU-native underneath.

API surface mirrors the reference python/paddle/fluid/__init__.py so user
programs written against fluid run here; execution compiles whole programs
to XLA instead of interpreting ops (see executor.py)."""
from . import core
from .core import (CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace, LoDTensor,
                   LoDTensorArray, Scope, is_compiled_with_cuda,
                   is_compiled_with_tpu)
from . import framework
from .framework import (Program, Variable, program_guard,
                        default_main_program, default_startup_program,
                        name_scope, cpu_places, cuda_places, tpu_places,
                        in_dygraph_mode, device_guard)
from . import unique_name
from . import ir
from . import initializer
from . import regularizer
from . import clip
from .clip import GradientClipByGlobalNorm, GradientClipByNorm, \
    GradientClipByValue
from .param_attr import ParamAttr, WeightNormParamAttr
from . import layers
from . import nets
from . import average
from . import install_check
from .layers.io import data
from . import backward
from .backward import append_backward, gradients
from . import optimizer
from . import executor
from .executor import Executor, FetchHandler, global_scope, scope_guard
from . import compiler
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from . import io
from .io import (save_vars, save_params, save_persistables, load_vars,
                 load_params, load_persistables, save_inference_model,
                 load_inference_model, save, load)
from . import dygraph
from . import metrics
from . import profiler
from .data_feeder import DataFeeder
from . import reader
from .reader import DataLoader
from . import contrib
from . import transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig
from .communicator import Communicator
from . import dataset
from .dataset import DatasetFactory, InMemoryDataset

Tensor = LoDTensor


def set_flags(d):
    core.set_flags(d)


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    return {n: core.get_flag(n) for n in names}


__all__ = [
    "core", "framework", "layers", "optimizer", "backward", "initializer",
    "regularizer", "clip", "io", "dygraph", "metrics", "profiler", "contrib",
    "Program", "Variable", "Executor", "CompiledProgram", "BuildStrategy",
    "ExecutionStrategy", "CPUPlace", "TPUPlace", "CUDAPlace",
    "CUDAPinnedPlace", "LoDTensor", "LoDTensorArray", "Scope", "ParamAttr",
    "WeightNormParamAttr", "DataFeeder", "DataLoader", "data",
    "program_guard", "default_main_program", "default_startup_program",
    "global_scope", "scope_guard", "append_backward", "gradients",
    "save_inference_model", "load_inference_model", "save", "load",
    "in_dygraph_mode", "cpu_places", "cuda_places", "tpu_places",
    "transpiler", "DistributeTranspiler", "DistributeTranspilerConfig",
    "Communicator", "dataset", "DatasetFactory", "InMemoryDataset",
]
