"""paddle_tpu.fluid — the Fluid-compatible front end, TPU-native underneath.

API surface mirrors the reference python/paddle/fluid/__init__.py so user
programs written against fluid run here; execution compiles whole programs
to XLA instead of interpreting ops (see executor.py)."""
from . import core
from .core import (CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace, LoDTensor,
                   LoDTensorArray, Scope, is_compiled_with_cuda,
                   is_compiled_with_tpu)
from . import framework
from .framework import (Program, Variable, program_guard,
                        default_main_program, default_startup_program,
                        name_scope, cpu_places, cuda_places, tpu_places,
                        in_dygraph_mode, device_guard)
from . import unique_name
from . import ir
from . import analysis
from . import initializer
from . import regularizer
from . import clip
from .clip import GradientClipByGlobalNorm, GradientClipByNorm, \
    GradientClipByValue
from .param_attr import ParamAttr, WeightNormParamAttr
from . import layers
from . import nets
from . import average
from . import install_check
from .layers.io import data
from . import backward
from .backward import append_backward, gradients
from . import optimizer
from . import executor
from .executor import Executor, FetchHandler, global_scope, scope_guard
from . import compiler
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from . import io
from .io import (save_vars, save_params, save_persistables, load_vars,
                 load_params, load_persistables, save_inference_model,
                 load_inference_model, save, load, save_checkpoint,
                 load_checkpoint, latest_checkpoint, validate_checkpoint)
from . import dygraph
from . import metrics
from . import profiler
from . import telemetry
from .data_feeder import DataFeeder
from . import reader
from .reader import DataLoader
from . import contrib
from . import transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig
from .communicator import Communicator
from . import dataset
from .dataset import DatasetFactory, InMemoryDataset

Tensor = LoDTensor


def memory_optimize(input_program=None, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """Deprecated no-op (reference: legacy memory_optimization_transpiler,
    already deprecated in v1.6+). XLA buffer assignment plans memory for
    the whole jitted step, so there is nothing to rewrite."""
    import warnings
    warnings.warn("fluid.memory_optimize is deprecated and a no-op on this "
                  "build: XLA plans memory inside the compiled step",
                  DeprecationWarning, stacklevel=2)


def release_memory(input_program, skip_opt_set=None):
    """Deprecated no-op — see memory_optimize."""
    import warnings
    warnings.warn("fluid.release_memory is deprecated and a no-op on this "
                  "build", DeprecationWarning, stacklevel=2)


def require_version(min_version, max_version=None):
    """Abort unless the installed version falls in [min, max] (reference:
    fluid/framework.py require_version)."""
    from .. import version as _v

    def parse(s):
        parts = str(s).replace("+", ".").split(".")
        nums = []
        for p in parts[:3]:
            nums.append(int(p) if p.isdigit() else 0)
        return tuple(nums + [0] * (3 - len(nums)))
    if not isinstance(min_version, str) or (
            max_version is not None and not isinstance(max_version, str)):
        raise TypeError("version arguments must be strings like '1.7.0'")
    cur = parse(_v.full_version)
    if cur < parse(min_version):
        raise Exception(
            f"installed version {_v.full_version} < required {min_version}")
    if max_version is not None and cur > parse(max_version):
        raise Exception(
            f"installed version {_v.full_version} > allowed {max_version}")


def load_op_library(lib_filename):
    """Reference loads a custom-op .so into the registry. Custom ops on
    this build are Python kernels registered via
    paddle_tpu.ops.registry.register_op — point users there."""
    raise NotImplementedError(
        "C++ custom-op libraries don't apply to the TPU build; register a "
        "JAX kernel with paddle_tpu.ops.registry.register_op instead")


def one_hot(input, depth, allow_out_of_range=False):
    """v1.7 unified one_hot (no trailing-1 dim required — one_hot_v2)."""
    from .layer_helper import LayerHelper
    helper = LayerHelper("one_hot_v2")
    out = helper.create_variable_for_type_inference(
        core.VarDesc.VarType.FP32)
    out.shape = tuple(input.shape) + (depth,)
    helper.append_op(type="one_hot_v2", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"depth": depth,
                            "allow_out_of_range": allow_out_of_range})
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """v1.7 unified embedding (ids without trailing-1 dim —
    lookup_table_v2)."""
    from .layer_helper import LayerHelper
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(attr=helper.param_attr, shape=size,
                                dtype=dtype, is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    pad = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    out.shape = tuple(input.shape) + (size[1],)
    helper.append_op(type="lookup_table_v2",
                     inputs={"W": [w], "Ids": [input]},
                     outputs={"Out": [out]},
                     attrs={"padding_idx": pad, "is_sparse": is_sparse,
                            "is_distributed": is_distributed})
    return out


def set_flags(d):
    core.set_flags(d)


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    return {n: core.get_flag(n) for n in names}


__all__ = [
    "core", "framework", "layers", "optimizer", "backward", "initializer",
    "regularizer", "clip", "io", "dygraph", "metrics", "profiler",
    "telemetry", "contrib",
    "Program", "Variable", "Executor", "CompiledProgram", "BuildStrategy",
    "ExecutionStrategy", "CPUPlace", "TPUPlace", "CUDAPlace",
    "CUDAPinnedPlace", "LoDTensor", "LoDTensorArray", "Scope", "ParamAttr",
    "WeightNormParamAttr", "DataFeeder", "DataLoader", "data",
    "program_guard", "default_main_program", "default_startup_program",
    "global_scope", "scope_guard", "append_backward", "gradients",
    "save_inference_model", "load_inference_model", "save", "load",
    "in_dygraph_mode", "cpu_places", "cuda_places", "tpu_places",
    "transpiler", "DistributeTranspiler", "DistributeTranspilerConfig",
    "Communicator", "dataset", "DatasetFactory", "InMemoryDataset",
    "memory_optimize", "release_memory", "require_version",
    "load_op_library", "one_hot", "embedding", "FetchHandler",
    "nets", "average", "install_check",
]
