"""Unified telemetry plane (docs/OBSERVABILITY.md) — the three legs the
rest of the repo's observability hangs off:

  * **distributed trace correlation** — a Dapper-style thread-local
    trace context (trace_id / span_id / parent). ``trace_scope``
    installs one; the profiler stamps it onto every recorded span, the
    PS RPC client ships it in the ``_trace`` header (ps_rpc), the
    VarServer installs it around handler execution, and the serving
    ingress accepts/mints ``X-Trace-Id`` — so one serving request or
    one training round is followable trainer→pserver→replica end to
    end.
  * **metrics registry** — Counter/Gauge/Histogram primitives with
    labels plus *views* over the repo's existing ``stats()`` dicts,
    exposed in Prometheus text format at the serving ingress
    ``GET /metrics`` and on the opt-in ``FLAGS_metrics_port``
    sidecar server every pserver/trainer can run.
  * **merged cluster timelines** — with ``FLAGS_trace_dir`` set, every
    process streams its profiler spans into a bounded ring-buffer
    chrome-trace shard (raw ``time.perf_counter`` timestamps +
    process/role metadata + the monotonic clock offsets measured in the
    ps_rpc ``_hello`` handshake); ``tools/timeline.py merge`` aligns
    the shards into one clock-corrected timeline keyed by trace id.

This module deliberately imports only ``core`` from the package (for
the FLAGS registry) so every other layer — profiler, ps_rpc, executor,
serving — can depend on it without cycles.
"""
from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import core

__all__ = [
    "TraceContext", "trace_scope", "current_trace", "new_trace_id",
    "new_span_id", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "REGISTRY", "note_clock_offset", "clock_offsets", "set_process_role",
    "process_role", "shard_active", "shard_record", "flush_trace_shard",
    "trace_shard_path", "start_metrics_server", "maybe_start_metrics_server",
    "metrics_server_port", "count_compile", "install_jax_compile_listener",
]

_LOG = logging.getLogger("paddle_tpu.telemetry")


# ---------------------------------------------------------------------------
# trace context (Dapper-style propagation)
# ---------------------------------------------------------------------------
class TraceContext:
    """One logical span: every profiler event recorded while a context
    is installed carries its (trace_id, span_id, parent_id)."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def __repr__(self):
        return (f"TraceContext(trace_id={self.trace_id!r}, "
                f"span_id={self.span_id!r}, parent_id={self.parent_id!r})")


_TRACE = threading.local()


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def current_trace() -> Optional[TraceContext]:
    """The TraceContext installed on THIS thread (None outside any
    trace_scope)."""
    return getattr(_TRACE, "ctx", None)


class trace_scope:
    """Install a trace context on this thread for the ``with`` body.

    * ``trace_scope()`` — continue the current trace with a CHILD span
      (or start a fresh root trace when none is installed).
    * ``trace_scope(trace_id=..., parent_span_id=...)`` — adopt a trace
      arriving from another process (RPC ``_trace`` header, HTTP
      ``X-Trace-Id``): same trace id, NEW span id parented on the
      caller's span — "same trace id, new span id" is the cross-process
      contract the propagation tests pin down.
    * ``trace_scope(adopt=ctx)`` — re-install an existing context
      verbatim on another thread (the sharded-RPC fan-out pool and the
      serving worker threads carry the submitting thread's context this
      way)."""

    def __init__(self, trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None,
                 adopt: Optional[TraceContext] = None):
        self._trace_id = trace_id
        self._parent = parent_span_id
        self._adopt = adopt
        self._prev: Optional[TraceContext] = None
        self.ctx: Optional[TraceContext] = None

    def __enter__(self) -> TraceContext:
        self._prev = current_trace()
        if self._adopt is not None:
            self.ctx = self._adopt
        elif self._trace_id is not None:
            self.ctx = TraceContext(self._trace_id, new_span_id(),
                                    self._parent)
        elif self._prev is not None:
            self.ctx = TraceContext(self._prev.trace_id, new_span_id(),
                                    self._prev.span_id)
        else:
            self.ctx = TraceContext(new_trace_id(), new_span_id(), None)
        _TRACE.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _TRACE.ctx = self._prev
        return False


# ---------------------------------------------------------------------------
# metrics registry (Prometheus-style exposition)
# ---------------------------------------------------------------------------
def _sanitize(name: str) -> str:
    out = []
    for ch in str(name):
        out.append(ch if (ch.isalnum() or ch in "_:") else "_")
    s = "".join(out)
    return s or "_"


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    parts = []
    for k, v in sorted(labels.items()):
        v = (str(v).replace("\\", "\\\\").replace('"', '\\"')
             .replace("\n", "\\n"))
        parts.append(f'{_sanitize(k)}="{v}"')
    return "{" + ",".join(parts) + "}"


class _Child:
    """One labeled instance of a metric family."""

    def __init__(self, family: "_MetricFamily", labels: Dict[str, str]):
        self._family = family
        self.labels_dict = labels
        self._lock = threading.Lock()
        self._value = 0.0
        # histogram state
        if family.kind == "histogram":
            self._bucket_counts = [0] * len(family.buckets)
            self._sum = 0.0
            self._count = 0

    # counter / gauge -----------------------------------------------------
    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1) -> None:
        if self._family.kind != "gauge":
            raise TypeError(f"{self._family.name}: dec() on a "
                            f"{self._family.kind}")
        with self._lock:
            self._value -= n

    def set(self, v: float) -> None:
        if self._family.kind != "gauge":
            raise TypeError(f"{self._family.name}: set() on a "
                            f"{self._family.kind}")
        with self._lock:
            self._value = v

    def value(self) -> float:
        with self._lock:
            v = self._value
        return int(v) if float(v).is_integer() else v

    def _reset(self) -> None:
        """Internal: zero the child (the serving engine's reset_stats
        contract predates the registry and keeps working as a view)."""
        with self._lock:
            self._value = 0.0
            if self._family.kind == "histogram":
                self._bucket_counts = [0] * len(self._family.buckets)
                self._sum = 0.0
                self._count = 0

    # histogram -----------------------------------------------------------
    def observe(self, v: float) -> None:
        if self._family.kind != "histogram":
            raise TypeError(f"{self._family.name}: observe() on a "
                            f"{self._family.kind}")
        with self._lock:
            for i, b in enumerate(self._family.buckets):
                if v <= b:
                    self._bucket_counts[i] += 1
            self._sum += v
            self._count += 1

    def histogram_state(self):
        with self._lock:
            return list(self._bucket_counts), self._sum, self._count


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0)


class _MetricFamily:
    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Tuple[str, ...], buckets=None):
        self.name = _sanitize(name)
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets or _DEFAULT_BUCKETS)) \
            if kind == "histogram" else ()
        self._children: Dict[Tuple[str, ...], _Child] = {}
        self._lock = threading.Lock()

    def labels(self, **kv) -> _Child:
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            ch = self._children.get(key)
            if ch is None:
                ch = self._children[key] = _Child(
                    self, dict(zip(self.labelnames, key)))
            return ch

    def remove(self, **kv) -> None:
        key = tuple(str(kv.get(n, "")) for n in self.labelnames)
        with self._lock:
            self._children.pop(key, None)

    # label-less convenience: family acts as its single child
    def _solo(self) -> _Child:
        if self.labelnames:
            raise ValueError(f"{self.name}: has labels "
                             f"{self.labelnames} — use .labels()")
        return self.labels()

    def inc(self, n: float = 1) -> None:
        self._solo().inc(n)

    def dec(self, n: float = 1) -> None:
        self._solo().dec(n)

    def set(self, v: float) -> None:
        self._solo().set(v)

    def observe(self, v: float) -> None:
        self._solo().observe(v)

    def value(self, **kv) -> float:
        return (self.labels(**kv) if kv else self._solo()).value()

    def children(self) -> List[_Child]:
        with self._lock:
            return list(self._children.values())


class Counter(_MetricFamily):
    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, "counter", help, labelnames)


class Gauge(_MetricFamily):
    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, "gauge", help, labelnames)
        self._fn: Optional[Callable[[], float]] = None

    def set_function(self, fn: Callable[[], float]) -> "Gauge":
        """Compute the (label-less) gauge at scrape time."""
        self._fn = fn
        return self


class Histogram(_MetricFamily):
    def __init__(self, name, help="", labelnames=(), buckets=None):
        super().__init__(name, "histogram", help, labelnames,
                         buckets=buckets)


def _flatten_stats(prefix: str, obj, out: List[Tuple[str, float]]):
    """Flatten a stats() dict into (metric_name, value) samples: nested
    keys join with '_' (sanitized), numeric leaves only — strings,
    lists and Nones are skipped (they are labels/evidence, not
    samples). This is what keeps the dict APIs authoritative while
    /metrics exposes the same numbers."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            _flatten_stats(f"{prefix}_{_sanitize(k)}", v, out)
        return
    if isinstance(obj, bool):
        out.append((prefix, int(obj)))
        return
    if isinstance(obj, (int, float)):
        out.append((prefix, obj))
        return
    # numpy scalars quack like floats without being instances
    try:
        import numpy as _np
        if isinstance(obj, _np.generic):
            out.append((prefix, obj.item()))
    except Exception:
        pass


class MetricsRegistry:
    """Process-global metric store. Two registration styles:

    * primitives — ``counter``/``gauge``/``histogram`` (get-or-create
      by name; kind conflicts raise) for NEW instrumentation;
    * views — ``register_view(prefix, fn, labels)`` bridges an
      existing ``stats()`` dict: ``fn()`` is called at scrape time and
      its numeric leaves are exposed as gauges named
      ``<prefix>_<joined keys>`` carrying ``labels``. The dict API
      stays the source of truth, so /metrics can never drift from
      ``stats()``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _MetricFamily] = {}
        self._views: List[Tuple[str, Callable[[], dict],
                                Dict[str, str], object]] = []

    # ------------------------------------------------------- primitives
    def _family(self, cls, name, help, labelnames, **kw) -> _MetricFamily:
        name = _sanitize(name)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = cls(
                    name, help=help, labelnames=labelnames, **kw)
            elif not isinstance(fam, cls) \
                    or tuple(labelnames) != fam.labelnames:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{fam.kind} with labels {fam.labelnames}")
            return fam

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._family(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._family(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=None) -> Histogram:
        return self._family(Histogram, name, help, labelnames,
                            buckets=buckets)

    def get(self, name) -> Optional[_MetricFamily]:
        with self._lock:
            return self._families.get(_sanitize(name))

    # ------------------------------------------------------------ views
    def register_view(self, prefix: str, fn: Callable[[], dict],
                      labels: Optional[Dict[str, str]] = None) -> object:
        """Register a stats-dict view; returns a handle for
        ``unregister_view``."""
        handle = object()
        with self._lock:
            self._views.append((_sanitize(prefix), fn,
                                dict(labels or {}), handle))
        return handle

    def unregister_view(self, handle) -> None:
        with self._lock:
            self._views = [v for v in self._views if v[3] is not handle]

    # ------------------------------------------------------- exposition
    def collect(self) -> Dict[str, Dict[str, Any]]:
        """name -> {type, help, samples: [(labels, value)]} — the
        structured form ``exposition`` renders (tests assert against
        this to dodge text parsing)."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            fams = list(self._families.values())
            views = list(self._views)
        for fam in fams:
            entry = out.setdefault(fam.name, {
                "type": fam.kind, "help": fam.help, "samples": []})
            for ch in fam.children():
                if fam.kind == "histogram":
                    counts, hsum, cnt = ch.histogram_state()
                    for b, c in zip(fam.buckets, counts):
                        entry["samples"].append((
                            {**ch.labels_dict, "le": repr(float(b))}, c))
                    entry["samples"].append((
                        {**ch.labels_dict, "le": "+Inf"}, cnt))
                    out.setdefault(fam.name + "_sum", {
                        "type": "gauge", "help": "", "samples": []
                    })["samples"].append((dict(ch.labels_dict), hsum))
                    out.setdefault(fam.name + "_count", {
                        "type": "gauge", "help": "", "samples": []
                    })["samples"].append((dict(ch.labels_dict), cnt))
                else:
                    entry["samples"].append(
                        (dict(ch.labels_dict), ch.value()))
            if isinstance(fam, Gauge) and fam._fn is not None:
                try:
                    entry["samples"].append(({}, fam._fn()))
                except Exception:
                    _LOG.exception("gauge function %s failed", fam.name)
        for prefix, fn, labels, _h in views:
            try:
                stats = fn() or {}
            except Exception:
                # a broken view must not break the whole scrape
                _LOG.exception("metrics view %s failed", prefix)
                continue
            samples: List[Tuple[str, float]] = []
            _flatten_stats(prefix, stats, samples)
            for name, value in samples:
                out.setdefault(name, {
                    "type": "gauge", "help": "", "samples": []
                })["samples"].append((dict(labels), value))
        return out

    def exposition(self) -> str:
        """Prometheus text format 0.0.4."""
        lines: List[str] = []
        for name, entry in sorted(self.collect().items()):
            if entry["help"]:
                lines.append(f"# HELP {name} {entry['help']}")
            lines.append(f"# TYPE {name} {entry['type']}")
            for labels, value in entry["samples"]:
                sample_name = (name + "_bucket"
                               if entry["type"] == "histogram" else name)
                lines.append(f"{sample_name}{_fmt_labels(labels)} "
                             f"{_fmt_value(value)}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every family and view (tests)."""
        with self._lock:
            self._families.clear()
            self._views.clear()


REGISTRY = MetricsRegistry()


# executor compile/retrace counters (docs/OBSERVABILITY.md "Step
# telemetry"): bumped at the executor's EXPLICIT jit-cache-miss sites.
# A "compile" is the first entry of a cache; a "retrace" is a later
# miss of an already-populated cache (a new bucket/LoD/program
# signature appearing after warm-up) — the scrapeable form of the
# serving plane's "steady state never recompiles" claim.
def count_compile(kind: str, retrace: bool = False) -> None:
    REGISTRY.counter(
        "executor_compiles_total",
        "jit-cache misses that triggered a trace+compile, by site",
        labelnames=("kind",)).labels(kind=kind).inc()
    if retrace:
        REGISTRY.counter(
            "executor_retraces_total",
            "cache misses AFTER the site already compiled once — new "
            "signature post-warm-up; flat in steady state",
            labelnames=("kind",)).labels(kind=kind).inc()


_JAX_LISTENER_LOCK = threading.Lock()
_JAX_LISTENER_INSTALLED = False


def install_jax_compile_listener() -> bool:
    """Register a jax.monitoring duration listener ONCE per process:
    every backend compile bumps ``jax_backend_compiles_total`` and
    (when the profiler records) emits a cat="compile" span — ground
    truth that catches retraces the executor's explicit cache counters
    cannot see (shape-driven retraces inside one jit). Zero cost on
    the steady-state path: jax only calls listeners when a compile
    actually happens."""
    global _JAX_LISTENER_INSTALLED
    with _JAX_LISTENER_LOCK:
        if _JAX_LISTENER_INSTALLED:
            return True
        try:
            import jax.monitoring as _mon

            counter = REGISTRY.counter(
                "jax_backend_compiles_total",
                "XLA backend compiles observed via jax.monitoring")

            def _on_duration(event: str, duration: float, **kw):
                if not event.endswith("backend_compile_duration"):
                    return
                counter.inc()
                from . import profiler as _profiler
                if _profiler.is_profiling():
                    now = time.perf_counter()
                    _profiler.record_span(
                        "compile:backend", now - float(duration), now,
                        cat="compile",
                        args={"seconds": round(float(duration), 6)})

            _mon.register_event_duration_secs_listener(_on_duration)
            _JAX_LISTENER_INSTALLED = True
            return True
        except Exception:  # older jax without monitoring — degrade
            _LOG.warning("jax.monitoring unavailable — compile spans "
                         "limited to executor cache-miss sites",
                         exc_info=True)
            _JAX_LISTENER_INSTALLED = True  # don't retry every call
            return False


# ---------------------------------------------------------------------------
# process identity + clock offsets (the timeline-merge substrate)
# ---------------------------------------------------------------------------
_PROCESS = {"role": None, "endpoint": None}
_PROCESS_LOCK = threading.Lock()

# endpoint -> (offset_s, rtt_s): offset = peer perf_counter - ours, the
# NTP-style estimate from the _hello handshake. Kept at MIN rtt (the
# tightest bound is the most accurate sample).
_OFFSETS: Dict[str, Tuple[float, float]] = {}
_OFFSETS_LOCK = threading.Lock()


def set_process_role(role: str, endpoint: Optional[str] = None,
                     override: bool = False) -> None:
    """Label this process for the trace shard metadata ('trainer0',
    'pserver', ...). First caller wins unless ``override`` — the
    PADDLE_TPU_TRACE_ROLE env (read at shard creation) beats both."""
    with _PROCESS_LOCK:
        if _PROCESS["role"] is None or override:
            _PROCESS["role"] = str(role)
        if endpoint is not None and (_PROCESS["endpoint"] is None
                                     or override):
            _PROCESS["endpoint"] = str(endpoint)


def process_role() -> Optional[str]:
    return os.environ.get("PADDLE_TPU_TRACE_ROLE") or _PROCESS["role"]


def note_clock_offset(endpoint: str, offset_s: float,
                      rtt_s: float) -> None:
    """Record a peer clock-offset sample from the _hello handshake:
    ``offset_s`` = peer's time.perf_counter() minus ours at the same
    instant (estimated at rtt/2)."""
    with _OFFSETS_LOCK:
        cur = _OFFSETS.get(endpoint)
        if cur is None or rtt_s <= cur[1]:
            _OFFSETS[endpoint] = (float(offset_s), float(rtt_s))


def clock_offsets() -> Dict[str, Tuple[float, float]]:
    with _OFFSETS_LOCK:
        return dict(_OFFSETS)


def reset_clock_offsets() -> None:
    with _OFFSETS_LOCK:
        _OFFSETS.clear()


# ---------------------------------------------------------------------------
# trace shard streaming (FLAGS_trace_dir)
# ---------------------------------------------------------------------------
class _ShardWriter:
    """Bounded ring buffer of chrome-trace events, flushed atomically to
    ``<trace_dir>/trace-<pid>.json``. Timestamps are RAW
    time.perf_counter microseconds (each process's own monotonic
    clock); the shard metadata carries a (wall, perf) anchor pair and
    the measured peer offsets so ``tools/timeline.py merge`` can
    clock-correct everything into one timeline."""

    _FLUSH_INTERVAL_S = 2.0

    def __init__(self, trace_dir: str):
        self.dir = trace_dir
        os.makedirs(trace_dir, exist_ok=True)
        self.path = os.path.join(trace_dir, f"trace-{os.getpid()}.json")
        max_events = max(
            1024, int(core.globals_["FLAGS_trace_shard_max_events"]))
        self._events: deque = deque(maxlen=max_events)
        self.dropped = 0
        self._lock = threading.Lock()
        # serializes snapshot+write+replace: two concurrent flushes
        # (atexit racing the background loop) would interleave writes
        # into the SAME .tmp inode and install a corrupt shard
        self._flush_lock = threading.Lock()
        self._since_flush = 0
        self._last_flush = time.perf_counter()
        # wall/perf anchor: maps this shard's raw perf timestamps onto
        # the wall clock — the merge fallback when no measured offset
        # links two shards (same-host shards share the wall clock)
        self._anchor_wall = time.time()
        self._anchor_perf = time.perf_counter()
        # a superseded writer (FLAGS_trace_dir re-pointed,
        # reset_trace_shard) is STOPPED: its flush thread exits and its
        # registered atexit flush becomes a no-op — atexit runs LIFO,
        # so a live old flush would overwrite the current writer's
        # shard with pre-reset events when the dir is reused
        self._stopped = False
        atexit.register(self.flush)
        # background flusher: a process that goes quiet (a pserver
        # parked in serve_forever) or dies hard (chaos SIGKILL) must
        # not lose its tail — the shard on disk stays at most
        # ~_FLUSH_INTERVAL_S stale regardless of record cadence
        t = threading.Thread(target=self._flush_loop,
                             name="telemetry-shard-flush", daemon=True)
        t.start()

    def _flush_loop(self):
        while not self._stopped:
            time.sleep(self._FLUSH_INTERVAL_S)
            if self._stopped:
                return
            with self._lock:
                dirty = self._since_flush > 0
            if dirty:
                self.flush()

    def stop(self) -> None:
        """Final flush, then deactivate (flush thread exits, the
        atexit hook no-ops)."""
        if not self._stopped:
            self.flush()
            self._stopped = True

    def record(self, name: str, start: float, end: float, tid: int,
               cat: str, args, trace: Optional[TraceContext]) -> None:
        ev = {"name": name, "ph": "X", "pid": os.getpid(), "tid": tid,
              "ts": start * 1e6, "dur": (end - start) * 1e6, "cat": cat}
        a = dict(args) if args else {}
        if trace is not None:
            a["trace_id"] = trace.trace_id
            a["span_id"] = trace.span_id
            if trace.parent_id:
                a["parent_id"] = trace.parent_id
        if a:
            ev["args"] = a
        # the recording (data-path) thread only appends and marks the
        # buffer dirty — the O(ring) JSON serialization always happens
        # on the background flusher (or an explicit flush), never as a
        # periodic stall inside an RPC handler or serving worker
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)
            self._since_flush += 1

    def flush(self) -> None:
        if self._stopped:
            return
        with self._flush_lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
            self._since_flush = 0
            self._last_flush = time.perf_counter()
        meta = {
            "pid": os.getpid(),
            "role": process_role() or f"proc{os.getpid()}",
            "endpoint": _PROCESS["endpoint"],
            "clock": "perf_counter_us",
            "anchor_wall_us": self._anchor_wall * 1e6,
            "anchor_perf_us": self._anchor_perf * 1e6,
            "dropped_events": dropped,
            "peer_offsets": {
                ep: {"offset_us": off * 1e6, "rtt_us": rtt * 1e6}
                for ep, (off, rtt) in clock_offsets().items()},
        }
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"traceEvents": events,
                           "displayTimeUnit": "ms",
                           "metadata": meta}, f)
            os.replace(tmp, self.path)
        except OSError:
            _LOG.exception("trace shard flush to %s failed", self.path)


_SHARD: Optional[_ShardWriter] = None
_SHARD_LOCK = threading.Lock()


def shard_active() -> bool:
    """True when FLAGS_trace_dir streaming is on — the profiler records
    spans (into the shard) even without start_profiler(). Gated on the
    FLAG alone: clearing it turns the recording overhead off even
    after a writer existed."""
    return bool(core.globals_["FLAGS_trace_dir"])


def _shard() -> Optional[_ShardWriter]:
    global _SHARD
    d = core.globals_["FLAGS_trace_dir"]
    if not d and _SHARD is not None:
        # flag cleared at runtime: final-flush and retire the writer
        # (its flush thread exits; the atexit hook no-ops)
        with _SHARD_LOCK:
            if _SHARD is not None:
                _SHARD.stop()
                _SHARD = None
        return None
    if _SHARD is not None:
        # a test that re-points FLAGS_trace_dir gets a fresh writer
        if d and _SHARD.dir != d:
            with _SHARD_LOCK:
                if _SHARD is not None and _SHARD.dir != d:
                    _SHARD.stop()
                    _SHARD = _ShardWriter(d)
        return _SHARD if d else None
    if not d:
        return None
    with _SHARD_LOCK:
        if _SHARD is None:
            _SHARD = _ShardWriter(d)
    return _SHARD


def shard_record(name: str, start: float, end: float, tid: int,
                 cat: str, args, trace=None) -> None:
    w = _shard()
    if w is not None:
        w.record(name, start, end, tid, cat, args, trace)


def flush_trace_shard() -> Optional[str]:
    """Force-write the shard now; returns its path (None when off)."""
    w = _shard()
    if w is None:
        return None
    w.flush()
    return w.path


def trace_shard_path() -> Optional[str]:
    w = _shard()
    return None if w is None else w.path


def reset_trace_shard() -> None:
    """Drop the writer (tests that re-point FLAGS_trace_dir)."""
    global _SHARD
    with _SHARD_LOCK:
        if _SHARD is not None:
            _SHARD.stop()
        _SHARD = None


# ---------------------------------------------------------------------------
# metrics sidecar server (FLAGS_metrics_port)
# ---------------------------------------------------------------------------
_METRICS_SRV = None
_METRICS_SRV_LOCK = threading.Lock()


def start_metrics_server(port: int = 0,
                         host: str = "127.0.0.1") -> Optional[int]:
    """Start the process's lightweight /metrics HTTP sidecar (idempotent
    — the first successful start wins; returns its bound port). Serves
    ``GET /metrics`` (Prometheus text) and ``GET /healthz``. Returns
    None when the port cannot be bound (another process on a shared
    box already owns it — logged, never fatal: observability must not
    take a pserver down)."""
    global _METRICS_SRV
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    with _METRICS_SRV_LOCK:
        if _METRICS_SRV is not None:
            return _METRICS_SRV.server_address[1]

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # stay off stderr
                _LOG.debug("metrics %s " + fmt,
                           self.client_address[0], *args)

            def do_GET(self):
                if self.path == "/metrics":
                    body = REGISTRY.exposition().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path == "/healthz":
                    body = b'{"status": "ok"}'
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        try:
            srv = ThreadingHTTPServer((host, int(port)), _Handler)
        except OSError as e:
            _LOG.warning("metrics server: cannot bind %s:%s (%r) — "
                         "metrics stay scrape-able via stats()/ingress",
                         host, port, e)
            return None
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever,
                         name="telemetry-metrics", daemon=True).start()
        _METRICS_SRV = srv
        return srv.server_address[1]


def maybe_start_metrics_server() -> Optional[int]:
    """Start the sidecar iff FLAGS_metrics_port > 0 (the opt-in hook
    pservers/trainers/ingresses call at startup). Idempotent."""
    port = int(core.globals_["FLAGS_metrics_port"])
    if port <= 0:
        return None
    return start_metrics_server(port)


def metrics_server_port() -> Optional[int]:
    srv = _METRICS_SRV
    return None if srv is None else srv.server_address[1]


def stop_metrics_server() -> None:
    global _METRICS_SRV
    with _METRICS_SRV_LOCK:
        srv, _METRICS_SRV = _METRICS_SRV, None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
