"""Static-analysis plane: the Program verifier (docs/ANALYSIS.md).

The reference framework validates operators eagerly — AddOp-time attr
checkers + InferShape (op_desc.cc, attribute_checker.h) — and runs IR
passes over the ProgramDesc before execution, so a malformed program
dies with a precise report instead of a deep runtime error. This build's
Python objects ARE the program (framework.py), so nothing checked them
until the executor traced — and the costliest defects of this repo's
history were all statically detectable (the PR 4 un-rewritten sparse
grad, the PR 5/7 donation/segment cross-path hazards, the PR 13 retrace
pins). This module is the regression wall: dataflow analysis over
``framework.Program`` blocks plus a distributed-protocol checker for
transpiled programs, emitting structured ``Diagnostic``s.

Three choke points call ``maybe_verify`` behind ``FLAGS_program_verify``
("" | "warn" | "error"):

  * ``Executor.run`` at the FIRST COMPILE of a program version (and the
    interpreter's once-per-version config build) — never per step;
  * the ``DistributeTranspiler`` on its own trainer-program output;
  * ``tools/verify_program.py`` over saved inference dirs (and
    ``io.save_inference_model`` unconditionally at level="error" — the
    PR 7 multi-block var-drop invariant as a permanent rule).

Diagnostics are counted as ``program_verify_diagnostics_total{rule,
severity}`` through the telemetry registry and the verifier's runtime is
recorded as a cat="segment" span (``verify:<where>``) so the first-compile
cost stays visible next to the segment/window spans it delays.

The concurrency half of the plane (lock-order cycles, blocking calls
under locks) is source-level, not program-level — see tools/lockcheck.py.
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Set, Tuple)

from . import core

_LOG = logging.getLogger("paddle_tpu.analysis")

__all__ = [
    "Diagnostic", "ProgramVerifyError", "verify_program", "maybe_verify",
    "enforce", "install_collector", "remove_collector", "rule_ids",
    "RULE_SEVERITY",
]


# --------------------------------------------------------------------------
# diagnostics
# --------------------------------------------------------------------------
@dataclass(frozen=True)
class Diagnostic:
    """One structured finding. ``op_idx`` indexes the op list of ``block``
    (feed/fetch ops included, matching ``Block.ops``); None for
    program-level findings."""

    rule: str
    severity: str                  # "error" | "warn"
    message: str
    block: int = 0
    op_idx: Optional[int] = None
    var: Optional[str] = None
    fix_hint: str = ""

    def format(self) -> str:
        loc = f"block {self.block}"
        if self.op_idx is not None:
            loc += f" op#{self.op_idx}"
        if self.var:
            loc += f" var '{self.var}'"
        s = f"[{self.severity}] {self.rule} @ {loc}: {self.message}"
        if self.fix_hint:
            s += f" (fix: {self.fix_hint})"
        return s


class ProgramVerifyError(RuntimeError):
    """Raised by level="error" verification when error-severity
    diagnostics are present. ``.diagnostics`` carries the full list
    (warn-severity included)."""

    def __init__(self, diagnostics: Sequence[Diagnostic], where: str):
        self.diagnostics = list(diagnostics)
        errs = [d for d in self.diagnostics if d.severity == "error"]
        lines = "\n  ".join(d.format() for d in errs[:16])
        more = f"\n  ... and {len(errs) - 16} more" if len(errs) > 16 else ""
        super().__init__(
            f"program verification failed at '{where}' with "
            f"{len(errs)} error(s):\n  {lines}{more}")


# rule id -> default severity. Rule ids are STABLE — the mutation corpus,
# allowlists and the telemetry label set key on them.
RULE_SEVERITY: Dict[str, str] = {
    "def-before-use": "error",
    "missing-var-desc": "error",
    "undeclared-sub-block-read": "warn",
    "dtype-mismatch": "warn",
    "shape-mismatch": "warn",
    "dead-op": "warn",
    "dead-var": "warn",
    "donation-safety": "error",
    "dist-local-sparse-grad": "error",
    "dist-barrier-pairing": "error",
    "dist-ps-round-tail": "warn",
    "retrace-partition-spec": "warn",
    "retrace-feed-shape": "warn",
}


def rule_ids() -> List[str]:
    return sorted(RULE_SEVERITY)


# --------------------------------------------------------------------------
# verification context
# --------------------------------------------------------------------------
class _Ctx:
    def __init__(self, program, feed_names, fetch_names, param_shardings,
                 segment_plan, where, scope=None):
        self.program = program
        # executor contract: a read-before-write var whose LoDTensor is
        # already initialized in the scope is STATE, not a def-before-use
        # bug (_classify_block_state) — when the caller has a scope, the
        # dataflow rule honors it
        self.scope = scope
        self.feed_names: Set[str] = set(feed_names or ())
        # fetch_names=None means "unknown" (transpiler choke point): rules
        # that would mistake an un-fetched-but-fetchable output for dead
        # code must skip (ir.Graph.is_internal documents the same hazard)
        self.fetch_known = fetch_names is not None
        self.fetch_names: Set[str] = set(fetch_names or ())
        self.param_shardings = dict(param_shardings or {})
        self.segment_plan = segment_plan
        self.where = where
        self.diags: List[Diagnostic] = []

    def emit(self, rule: str, message: str, *, block: int = 0,
             op_idx: Optional[int] = None, var: Optional[str] = None,
             fix_hint: str = "", severity: Optional[str] = None) -> None:
        self.diags.append(Diagnostic(
            rule=rule, severity=severity or RULE_SEVERITY[rule],
            message=message, block=block, op_idx=op_idx, var=var,
            fix_hint=fix_hint))


def _sub_blocks(op) -> List[Any]:
    """Block-valued attrs of ``op`` (sub_block, optimize_blocks, ...)."""
    from .framework import Block
    subs: List[Any] = []
    for val in op.attrs.values():
        if isinstance(val, Block):
            subs.append(val)
        elif isinstance(val, (list, tuple)) and val \
                and isinstance(val[0], Block):
            subs.extend(val)
    return subs


def _is_loop_op(op_type: str) -> bool:
    # loop bodies have carried values: a sub-block write is visible at the
    # top of the NEXT iteration, so strict program-order def-before-use
    # does not apply inside them
    return op_type.startswith("while") or op_type.startswith("recurrent")


def _all_writes(block) -> Set[str]:
    written: Set[str] = set()
    stack = [block]
    while stack:
        b = stack.pop()
        for op in b.ops:
            written.update(op.output_arg_names)
            stack.extend(_sub_blocks(op))
    return written


def _reads_with_subs(op) -> Set[str]:
    names = set(op.input_arg_names)
    stack = list(_sub_blocks(op))
    while stack:
        b = stack.pop()
        for sop in b.ops:
            names.update(sop.input_arg_names)
            stack.extend(_sub_blocks(sop))
    return names


def _is_sentinel(name: str) -> bool:
    """Names that are slot placeholders, not variables: the backward
    pass's @EMPTY@ grad sentinel and @DEPENDENCY control-dep markers
    (framework.py CONTROL_DEP_VAR_PREFIX) never get a VarDesc."""
    return name == "@EMPTY@" or name.startswith("@DEPENDENCY")


def _resolvable(block, name: str):
    """VarDesc for ``name`` visible from ``block`` (walking parents),
    falling back to a whole-program scan — transpiler/backward-built
    blocks sometimes reference vars declared in sibling blocks; the PR 7
    rule is about descs EXISTING, not about the exact block chain."""
    v = block._find_var_recursive(name)
    if v is not None:
        return v
    for b in block.program.blocks:
        if name in b.vars:
            return b.vars[name]
    return None


# --------------------------------------------------------------------------
# rule: dataflow (def-before-use, missing-var-desc,
#                 undeclared-sub-block-read)
# --------------------------------------------------------------------------
def _check_dataflow(ctx: _Ctx) -> None:
    program = ctx.program
    defined: Set[str] = set(ctx.feed_names) | {"feed", "fetch"}
    _walk_block(ctx, program.global_block(), defined, in_loop=False,
                visited=set())


def _walk_block(ctx: _Ctx, block, defined: Set[str], in_loop: bool,
                visited: Set[int]) -> None:
    if id(block) in visited:
        return
    visited.add(id(block))
    local = set(defined)
    if in_loop:
        local |= _all_writes(block)
    reported: Set[str] = set()
    for idx, op in enumerate(block.ops):
        if op.type == "feed":
            local.update(op.output_arg_names)
            continue
        if op.type == "fetch":
            continue
        for name in op.input_arg_names:
            if _is_sentinel(name):
                continue
            v = _resolvable(block, name)
            if v is None:
                if name not in reported:
                    reported.add(name)
                    ctx.emit(
                        "missing-var-desc",
                        f"op '{op.type}' references '{name}' but no "
                        "VarDesc for it is reachable from this block — "
                        "a program serialized like this fails the native "
                        "load validation (the PR 7 save var-drop hazard)",
                        block=block.idx, op_idx=idx, var=name,
                        fix_hint="declare the var in a visible block or "
                                 "stop dropping it from the saved program")
                continue
            if name in local or name in reported:
                continue
            if getattr(v, "persistable", False) or getattr(v, "is_data",
                                                           False) \
                    or getattr(v, "need_check_feed", False):
                local.add(name)
                continue
            if ctx.scope is not None:
                sv = ctx.scope.find_var(name)
                if sv is not None and sv.is_initialized():
                    local.add(name)   # pre-seeded state (executor rule)
                    continue
            reported.add(name)
            ctx.emit(
                "def-before-use",
                f"op '{op.type}' reads non-persistable '{name}' before "
                "any producer wrote it (and it is not a feed/data var)",
                block=block.idx, op_idx=idx, var=name,
                fix_hint="feed it, mark it persistable state, or reorder "
                         "the producing op before this one")
        subs = _sub_blocks(op)
        if subs:
            declared = set(op.input_arg_names)
            sub_loop = in_loop or _is_loop_op(op.type)
            for sb in subs:
                _check_external_reads(ctx, op, idx, block, sb, local,
                                      declared, sub_loop)
                _walk_block(ctx, sb, local, sub_loop, visited)
            # conservative: sub-block writes become visible after the op
            # (the interpreter writes them through the scope)
            for sb in subs:
                local |= _all_writes(sb)
        local.update(op.output_arg_names)


def _check_external_reads(ctx: _Ctx, op, op_idx: int, block, sub,
                          outer_defined: Set[str], declared: Set[str],
                          sub_loop: bool) -> None:
    """The declared-external-reads invariant (PR 7): a sub-block op
    reading a NON-persistable var of an outer block should see that var
    listed in the parent op's input slots — prune/var-drop/feed analysis
    all reason about the parent op's declared interface."""
    produced: Set[str] = set()
    if sub_loop:
        produced |= _all_writes(sub)
    for sop in sub.ops:
        for name in sop.input_arg_names:
            if name in produced or name in declared or _is_sentinel(name):
                continue
            if name in sub.vars:      # sub-block-local declaration
                continue
            v = _resolvable(sub, name)
            if v is None:
                continue              # missing-var-desc covers it
            if getattr(v, "persistable", False) or getattr(v, "is_data",
                                                           False) \
                    or getattr(v, "need_check_feed", False):
                continue
            declared.add(name)        # report once per parent op
            ctx.emit(
                "undeclared-sub-block-read",
                f"sub-block op '{sop.type}' reads outer var '{name}' "
                f"that parent op '{op.type}' does not declare in its "
                "input slots",
                block=sub.idx, var=name,
                fix_hint="add the var to the parent op's input slots so "
                         "prune/save interface analysis sees the read")
        produced.update(sop.output_arg_names)


# --------------------------------------------------------------------------
# rule: dtype / shape propagation
# --------------------------------------------------------------------------
_SAME_DTYPE_OPS = frozenset({
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_pow", "elementwise_min",
    "elementwise_max", "sum", "concat", "mul", "matmul", "matmul_v2",
})


def _np_dtype_name(dtype) -> Optional[str]:
    try:
        import numpy as np
        return np.dtype(core.dtype_to_np(dtype)).name
    except Exception:
        return None


def _static_dims(shape) -> Optional[Tuple[int, ...]]:
    if shape is None:
        return None
    t = tuple(int(d) for d in shape)
    return t if t else None


def _check_dtype_shape(ctx: _Ctx) -> None:
    for block in ctx.program.blocks:
        for idx, op in enumerate(block.ops):
            if op.type in _SAME_DTYPE_OPS:
                _check_same_dtype(ctx, block, idx, op)
            if op.type == "cast":
                _check_cast(ctx, block, idx, op)
            if op.type == "mul":
                _check_mul_shape(ctx, block, idx, op)
            elif op.type in ("matmul", "matmul_v2"):
                _check_matmul_shape(ctx, block, idx, op)


def _check_same_dtype(ctx: _Ctx, block, idx, op) -> None:
    seen: Dict[str, str] = {}
    for slot in ("X", "Y"):
        for name in op.input(slot):
            v = _resolvable(block, name)
            dt = _np_dtype_name(getattr(v, "dtype", None)) if v else None
            if dt is not None:
                seen[name] = dt
    kinds = set(seen.values())
    if len(kinds) > 1:
        detail = ", ".join(f"{n}:{d}" for n, d in sorted(seen.items()))
        ctx.emit(
            "dtype-mismatch",
            f"op '{op.type}' mixes input dtypes ({detail}) — the traced "
            "kernel will silently promote (or XLA will reject) what the "
            "reference validates at AddOp time",
            block=block.idx, op_idx=idx, var=next(iter(seen)),
            fix_hint="insert an explicit cast op")


def _check_cast(ctx: _Ctx, block, idx, op) -> None:
    outs = op.output("Out")
    if not outs:
        return
    v = _resolvable(block, outs[0])
    want = op.attr("out_dtype")
    if v is None or want is None or v.dtype is None:
        return
    a, b = _np_dtype_name(v.dtype), _np_dtype_name(want)
    if a and b and a != b:
        ctx.emit(
            "dtype-mismatch",
            f"cast declares out_dtype={b} but output var '{outs[0]}' is "
            f"declared {a}",
            block=block.idx, op_idx=idx, var=outs[0],
            fix_hint="align the var desc dtype with the cast attr")


def _flat_dim(shape: Tuple[int, ...], start: int, stop: int) -> int:
    """Product of dims [start:stop); -1 (unknown) poisons to -1."""
    prod = 1
    for d in shape[start:stop]:
        if d <= 0:
            return -1
        prod *= d
    return prod


def _check_mul_shape(ctx: _Ctx, block, idx, op) -> None:
    xs, ys = op.input("X"), op.input("Y")
    if not xs or not ys:
        return
    xv, yv = _resolvable(block, xs[0]), _resolvable(block, ys[0])
    xsh = _static_dims(getattr(xv, "shape", None)) if xv else None
    ysh = _static_dims(getattr(yv, "shape", None)) if yv else None
    if not xsh or not ysh:
        return
    xn = int(op.attr("x_num_col_dims") or 1)
    yn = int(op.attr("y_num_col_dims") or 1)
    inner_x = _flat_dim(xsh, xn, len(xsh))
    inner_y = _flat_dim(ysh, 0, yn)
    if inner_x > 0 and inner_y > 0 and inner_x != inner_y:
        ctx.emit(
            "shape-mismatch",
            f"mul inner dims disagree: {xs[0]}{list(xsh)} flattened at "
            f"x_num_col_dims={xn} gives K={inner_x}, {ys[0]}{list(ysh)} "
            f"gives K={inner_y}",
            block=block.idx, op_idx=idx, var=xs[0],
            fix_hint="fix the weight shape or the num_col_dims attrs")


def _check_matmul_shape(ctx: _Ctx, block, idx, op) -> None:
    xs, ys = op.input("X"), op.input("Y")
    if not xs or not ys:
        return
    xv, yv = _resolvable(block, xs[0]), _resolvable(block, ys[0])
    xsh = _static_dims(getattr(xv, "shape", None)) if xv else None
    ysh = _static_dims(getattr(yv, "shape", None)) if yv else None
    if not xsh or not ysh or len(xsh) < 2 or len(ysh) < 2:
        return
    tx = bool(op.attr("transpose_X") or op.attr("trans_x"))
    ty = bool(op.attr("transpose_Y") or op.attr("trans_y"))
    kx = xsh[-2] if tx else xsh[-1]
    ky = ysh[-1] if ty else ysh[-2]
    if kx > 0 and ky > 0 and kx != ky:
        ctx.emit(
            "shape-mismatch",
            f"matmul contraction dims disagree: {xs[0]}{list(xsh)} "
            f"(transpose_X={tx}) K={kx} vs {ys[0]}{list(ysh)} "
            f"(transpose_Y={ty}) K={ky}",
            block=block.idx, op_idx=idx, var=xs[0],
            fix_hint="fix the operand shapes or transpose attrs")


# --------------------------------------------------------------------------
# rule: dead ops / dead vars
# --------------------------------------------------------------------------
def _op_has_side_effects(op) -> bool:
    from .ir import op_island_reason
    # island ops (stateful kernels, host-input readers, control flow,
    # unregistered types) and the distributed data-plane ops act beyond
    # their declared outputs — never dead
    return op_island_reason(op) is not None


def _check_dead(ctx: _Ctx) -> None:
    if not ctx.fetch_known:
        # consumer-less outputs may be fetch targets of a later run — the
        # fetch list is not part of the program (ir.Graph.is_internal)
        return
    block = ctx.program.global_block()
    indexed = [(i, op) for i, op in enumerate(block.ops)
               if op.type not in ("feed", "fetch")]
    live_names = set(ctx.fetch_names)
    keep = {}
    persistable = {n for n, v in block.vars.items()
                   if getattr(v, "persistable", False)}
    for i, op in indexed:
        if _op_has_side_effects(op) \
                or any(n in persistable for n in op.output_arg_names):
            keep[i] = True
    for i, op in reversed(indexed):
        if keep.get(i) or (set(op.output_arg_names) & live_names):
            keep[i] = True
            live_names.update(_reads_with_subs(op))
    for i, op in indexed:
        if not keep.get(i):
            outs = op.output_arg_names
            if op.type.endswith("_grad") or (
                    outs and all(o.endswith("@GRAD") or o == "@EMPTY@"
                                 for o in outs)):
                # mechanically generated backward ops compute grads for
                # EVERY differentiable input, and append_backward seeds
                # a fill for every loss grad; unconsumed leaf grads /
                # seeds over severed grad paths are the documented
                # backward contract and XLA DCEs them — not dead code
                # anyone wrote (docs/ANALYSIS.md "dead-op")
                continue
            ctx.emit(
                "dead-op",
                f"op '{op.type}' outputs "
                f"{sorted(op.output_arg_names)[:4]} are never read, "
                "fetched, or persisted",
                block=0, op_idx=i,
                var=(op.output_arg_names[0] if op.output_arg_names
                     else None),
                fix_hint="remove the op or fetch its output")

    referenced: Set[str] = set()
    for b in ctx.program.blocks:
        for op in b.ops:
            referenced.update(op.input_arg_names)
            referenced.update(op.output_arg_names)
    for name, v in block.vars.items():
        if name in referenced or name in ("feed", "fetch"):
            continue
        if getattr(v, "persistable", False) or getattr(v, "is_data", False):
            continue
        if name in ctx.feed_names or name in ctx.fetch_names:
            continue
        ctx.emit(
            "dead-var",
            f"var '{name}' is referenced by no op in any block",
            block=0, var=name,
            fix_hint="drop it (ir.Graph.drop_orphan_vars) or wire it up")


# --------------------------------------------------------------------------
# rule: donation safety (cross-checked against a segment plan)
# --------------------------------------------------------------------------
def _plan_entry(seg) -> Dict[str, Any]:
    if isinstance(seg, dict):
        return {"kind": seg.get("kind"), "start": int(seg.get("start", 0)),
                "stop": int(seg.get("stop", 0)),
                "n_ops": int(seg.get("stop", 0)) - int(seg.get("start", 0)),
                "out_names": tuple(seg.get("out_names", ()) or ()),
                "donated_names": tuple(seg.get("donated_names", ()) or ())}
    return {"kind": seg.kind, "start": seg.start, "stop": seg.stop,
            "n_ops": len(seg.ops),
            "out_names": tuple(getattr(seg, "out_names", ()) or ()),
            "donated_names": tuple(getattr(seg, "donated_names", ()) or ())}


def _check_donation(ctx: _Ctx) -> None:
    """A buffer donated by a compiled segment is DELETED when the jitted
    step runs — any later consumer must read the segment's returned
    output, so the name must be on the segment's out list. Cross-checks
    the plan the segmented executor actually built (or a
    ``ir.analyze_block_segments`` summary extended with out/donated
    names) against the CURRENT program — the drift between the two is
    exactly the PR 5/7 review-round hazard class, and the regression wall
    ROADMAP item 5's executor lowering refactor lands behind."""
    if ctx.segment_plan is None:
        return
    block = ctx.program.global_block()
    ops = [op for op in block.ops if op.type not in ("feed", "fetch")]
    segs = [_plan_entry(s) for s in ctx.segment_plan]
    covered = sum(s["n_ops"] for s in segs)
    if covered != len(ops):
        ctx.emit(
            "donation-safety",
            f"segment plan covers {covered} ops but the program has "
            f"{len(ops)} — the program changed since the plan was built, "
            "so donation/liveness decisions are stale",
            fix_hint="rebuild the segment plan (bump program version and "
                     "let the executor recompile) before running")
        return
    guard_select = (core.globals_["FLAGS_check_nan_inf"]
                    and core.globals_["FLAGS_nan_inf_action"]
                    in ("skip", "rollback"))
    persistable = {n for n, v in block.vars.items()
                   if getattr(v, "persistable", False)}
    for seg in segs:
        if seg["kind"] != "compiled" or not seg["donated_names"]:
            continue
        if guard_select:
            ctx.emit(
                "donation-safety",
                f"segment [{seg['start']}:{seg['stop']}) donates "
                f"{list(seg['donated_names'])[:4]} while the numeric "
                "fault guard's select action needs the pre-step buffers "
                "alive until the end-of-step discard (the PR 5 "
                "donation/guard hazard)",
                fix_hint="build the plan with per-segment donation "
                         "disabled under skip/rollback actions")
            continue
        out = set(seg["out_names"])
        later_reads: Set[str] = set()
        for op in ops[seg["stop"]:]:
            later_reads |= _reads_with_subs(op)
        for n in seg["donated_names"]:
            needed = (n in later_reads or n in ctx.fetch_names
                      or n in persistable)
            if needed and n not in out:
                ctx.emit(
                    "donation-safety",
                    f"'{n}' is donated (buffer deleted) by segment "
                    f"[{seg['start']}:{seg['stop']}) but a later "
                    "op/island, the fetch list, or the persistable "
                    "writeback still needs it and it is not among the "
                    "segment's outputs",
                    var=n,
                    fix_hint="return the updated value from the segment "
                             "(out_names) or stop donating the buffer")


# --------------------------------------------------------------------------
# rule: distributed protocol (transpiled programs)
# --------------------------------------------------------------------------
def _check_distributed(ctx: _Ctx) -> None:
    block = ctx.program.global_block()
    indexed = [(i, op) for i, op in enumerate(block.ops)]
    send_idx = [i for i, op in indexed if op.type == "send"]
    sb_idx = [i for i, op in indexed if op.type == "send_barrier"]
    recv_idx = [i for i, op in indexed if op.type == "recv"]
    fb_idx = [i for i, op in indexed if op.type == "fetch_barrier"]
    psr_idx = [i for i, op in indexed if op.type == "ps_round"]

    # tables served by the PS plane: anything a distributed lookup/grad
    # names (the transpiler stamps table_names + W on both rewrites)
    dist_tables: Set[str] = set()
    for _i, op in indexed:
        if op.type in ("distributed_lookup_table",
                       "distributed_lookup_table_grad"):
            dist_tables.update(op.input("W"))
            dist_tables.update(op.attr("table_names") or ())

    # --- the PR 4 bug as a permanent rule: a LOCAL sparse lookup/grad on
    # a pserver-hosted table silently drops the update on the trainer
    # floor — the embedding never trains
    for i, op in indexed:
        if op.type in ("lookup_table_grad", "lookup_table_v2_grad") \
                and op.input("W") and op.input("W")[0] in dist_tables:
            ctx.emit(
                "dist-local-sparse-grad",
                f"local '{op.type}' on pserver-hosted table "
                f"'{op.input('W')[0]}' — the sparse update never crosses "
                "the wire (the PR 4 pserver-embeddings-never-train bug)",
                op_idx=i, var=op.input("W")[0],
                fix_hint="rewrite to distributed_lookup_table_grad "
                         "(row-sharded remote pushes)")
        elif op.type in ("lookup_table", "lookup_table_v2") \
                and op.input("W") and op.input("W")[0] in dist_tables:
            ctx.emit(
                "dist-local-sparse-grad",
                f"local '{op.type}' on pserver-hosted table "
                f"'{op.input('W')[0]}' — the rows live on the pservers; "
                "a local lookup reads a stale or absent trainer copy",
                op_idx=i, var=op.input("W")[0],
                fix_hint="rewrite to distributed_lookup_table")

    # --- send/send_barrier/recv/fetch_barrier pairing & ordering. A
    # program with NO barrier ops is async-mode (legitimate); any barrier
    # present means the sync protocol applies in full.
    is_sync = bool(sb_idx or fb_idx)
    if is_sync:
        if send_idx and not sb_idx:
            ctx.emit(
                "dist-barrier-pairing",
                "sync trainer program has send ops but no send_barrier — "
                "pservers defer grad application to the barrier release; "
                "sparse-only shards would never train",
                op_idx=send_idx[0],
                fix_hint="append send_barrier after the last send "
                         "(endpoints = EVERY pserver)")
        if recv_idx and not fb_idx:
            ctx.emit(
                "dist-barrier-pairing",
                "sync trainer program has recv ops but no fetch_barrier — "
                "the next step's sends can interleave with this step's "
                "pulls on the wire",
                op_idx=recv_idx[0],
                fix_hint="append fetch_barrier after the last recv")
        if len(sb_idx) > 1:
            ctx.emit("dist-barrier-pairing",
                     f"{len(sb_idx)} send_barrier ops in one program",
                     op_idx=sb_idx[1],
                     fix_hint="exactly one per sync round")
        if len(fb_idx) > 1:
            ctx.emit("dist-barrier-pairing",
                     f"{len(fb_idx)} fetch_barrier ops in one program",
                     op_idx=fb_idx[1],
                     fix_hint="exactly one per sync round")
        if sb_idx:
            for i in send_idx:
                if i > sb_idx[0]:
                    ctx.emit(
                        "dist-barrier-pairing",
                        "send op AFTER send_barrier — its grad lands in "
                        "the NEXT round's reduce window",
                        op_idx=i,
                        fix_hint="move every send before the barrier")
            for i in recv_idx:
                if i < sb_idx[0]:
                    ctx.emit(
                        "dist-barrier-pairing",
                        "recv op BEFORE send_barrier — it pulls params "
                        "from before this round's grads applied",
                        op_idx=i,
                        fix_hint="move every recv after send_barrier")
        if fb_idx:
            for i in recv_idx:
                if i > fb_idx[-1]:
                    ctx.emit(
                        "dist-barrier-pairing",
                        "recv op AFTER fetch_barrier — it races the next "
                        "round's updates",
                        op_idx=i,
                        fix_hint="move every recv before fetch_barrier")
            if sb_idx and fb_idx[0] < sb_idx[0]:
                ctx.emit(
                    "dist-barrier-pairing",
                    "fetch_barrier precedes send_barrier",
                    op_idx=fb_idx[0],
                    fix_hint="order: sends, send_barrier, recvs, "
                             "fetch_barrier")

    # --- ps_round tail consistency (async overlap plane)
    if psr_idx:
        if send_idx or sb_idx or recv_idx or fb_idx:
            ctx.emit(
                "dist-ps-round-tail", severity="error",
                message="program mixes a ps_round op with the inline "
                        "send/barrier/recv tail — the round would run "
                        "twice against the same pserver reduce window",
                op_idx=psr_idx[0],
                fix_hint="the async-overlap rewrite REPLACES the 4-op "
                         "tail with one ps_round")
        if len(psr_idx) > 1:
            ctx.emit("dist-ps-round-tail", severity="error",
                     message=f"{len(psr_idx)} ps_round ops in one "
                             "program — one round per step",
                     op_idx=psr_idx[1],
                     fix_hint="exactly one ps_round per trainer step")
    elif is_sync and send_idx \
            and int(core.globals_["FLAGS_async_staleness"]) > 0:
        ctx.emit(
            "dist-ps-round-tail",
            f"FLAGS_async_staleness="
            f"{core.globals_['FLAGS_async_staleness']} but the program "
            "carries the inline sync tail (no ps_round op) — the overlap "
            "plane never engages and every step pays the full wire wait",
            op_idx=send_idx[0],
            fix_hint="transpile with DistributeTranspilerConfig."
                     "async_overlap=True (or set the staleness flag "
                     "BEFORE transpiling)")


# --------------------------------------------------------------------------
# rule: retrace lints (the PR 13 steady-state pins)
# --------------------------------------------------------------------------
def _check_retrace(ctx: _Ctx) -> None:
    for pname, spec in sorted(ctx.param_shardings.items()):
        try:
            entries = tuple(spec)
        except TypeError:
            continue
        if entries and entries[-1] is None:
            ctx.emit(
                "retrace-partition-spec",
                f"sharding for '{pname}' uses the long-form "
                f"PartitionSpec {entries!r} with trailing None dims — "
                "NamedSharding __eq__ (the jit cache key) treats "
                "P('pp') != P('pp', None), so mixing forms forks the "
                "cache and retraces every window (PR 13 pin)",
                var=pname,
                fix_hint="drop trailing None dims: use the short form "
                         "everywhere")
    seen: Set[str] = set()
    for block in ctx.program.blocks:
        for name, v in block.vars.items():
            if name in seen:
                continue
            if not (getattr(v, "is_data", False)
                    or getattr(v, "need_check_feed", False)):
                continue
            shape = tuple(getattr(v, "shape", ()) or ())
            if any(d == -1 for d in shape[1:]):
                seen.add(name)
                ctx.emit(
                    "retrace-feed-shape",
                    f"feed var '{name}' is shape-polymorphic beyond the "
                    f"batch dim (shape {list(shape)}) — every distinct "
                    "concrete shape is a new jit signature, so windowed "
                    "runs retrace in steady state (PR 13 pin)",
                    block=block.idx, var=name,
                    fix_hint="pad/bucket the trailing dims to a fixed "
                             "set of shapes")


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------
_CHECKS: List[Callable[[_Ctx], None]] = [
    _check_dataflow,
    _check_dtype_shape,
    _check_dead,
    _check_donation,
    _check_distributed,
    _check_retrace,
]


def verify_program(program, *, feed_names: Iterable[str] = (),
                   fetch_names: Optional[Iterable[str]] = None,
                   param_shardings: Optional[Dict[str, Any]] = None,
                   segment_plan: Optional[Sequence[Any]] = None,
                   rules: Optional[Iterable[str]] = None,
                   where: str = "api", scope=None) -> List[Diagnostic]:
    """Run every verifier rule over ``program`` and return the
    diagnostics (pure — no logging, no counters, no raising; see
    ``enforce``/``maybe_verify`` for policy).

    ``fetch_names=None`` means the fetch list is UNKNOWN (dead-code rules
    skip — a consumer-less output may be a later run's fetch target).
    ``segment_plan`` enables the donation-safety cross-check: pass the
    segmented executor's ``cb.segments`` (or dicts with kind/start/stop/
    out_names/donated_names). ``rules`` filters to a subset of
    ``rule_ids()``."""
    ctx = _Ctx(program, feed_names, fetch_names, param_shardings,
               segment_plan, where, scope=scope)
    for check in _CHECKS:
        check(ctx)
    diags = ctx.diags
    if rules is not None:
        wanted = set(rules)
        diags = [d for d in diags if d.rule in wanted]
    return diags


# fixture/test hooks: each enforced diagnostic is handed to every
# installed collector (tests/conftest.py's opt-in autouse fixture)
_COLLECTORS: List[Callable[[Diagnostic], None]] = []


def install_collector(fn: Callable[[Diagnostic], None]):
    _COLLECTORS.append(fn)
    return fn


def remove_collector(fn) -> None:
    try:
        _COLLECTORS.remove(fn)
    except ValueError:
        pass


def enforce(diags: Sequence[Diagnostic], level: str,
            where: str = "api") -> List[Diagnostic]:
    """Apply the ``FLAGS_program_verify`` policy to ``diags``: count every
    diagnostic through the telemetry registry, log warn-level lines, call
    the installed collectors, and raise ``ProgramVerifyError`` at
    level="error" when error-severity diagnostics exist."""
    if level not in ("warn", "error"):
        raise ValueError(
            f"verify level must be 'warn' or 'error', got {level!r}")
    if diags:
        from . import telemetry
        counter = telemetry.REGISTRY.counter(
            "program_verify_diagnostics_total",
            "Program verifier diagnostics by rule and severity",
            labelnames=("rule", "severity"))
        for d in diags:
            counter.labels(rule=d.rule, severity=d.severity).inc()
            _LOG.warning("program-verify[%s]: %s", where, d.format())
            for fn in list(_COLLECTORS):
                fn(d)
    if level == "error" and any(d.severity == "error" for d in diags):
        raise ProgramVerifyError(diags, where)
    return list(diags)


def maybe_verify(program, where: str, *, feed_names: Iterable[str] = (),
                 fetch_names: Optional[Iterable[str]] = None,
                 param_shardings: Optional[Dict[str, Any]] = None,
                 segment_plan: Optional[Sequence[Any]] = None,
                 level: Optional[str] = None, scope=None
                 ) -> Optional[List[Diagnostic]]:
    """Choke-point entry: verify ``program`` ONCE per (program version,
    choke point) when ``FLAGS_program_verify`` (or an explicit ``level``)
    asks for it. Steady state pays one dict probe per first-compile — the
    flag's no-per-step-cost contract. A level="error" failure is NOT
    cached, so every retry re-verifies and re-raises."""
    if level is None:
        level = str(core.globals_["FLAGS_program_verify"] or "")
    if not level:
        return None
    if level not in ("warn", "error"):
        raise ValueError(
            f"FLAGS_program_verify must be ''|'warn'|'error', "
            f"got {level!r}")
    cache = program.__dict__.setdefault("_verify_versions", {})
    key = (program._version, where)
    if key in cache:
        return None
    t0 = time.perf_counter()
    diags = verify_program(
        program, feed_names=feed_names, fetch_names=fetch_names,
        param_shardings=param_shardings, segment_plan=segment_plan,
        where=where, scope=scope)
    t1 = time.perf_counter()
    from . import profiler as _profiler
    # cat="segment": the verifier runs exactly where segment compiles do
    # (first compile of a program version) — its cost lands beside them
    # in the chrome trace instead of hiding in the first step's latency
    _profiler.record_span(
        f"verify:{where}", t0, t1, cat="segment",
        args={"where": where, "level": level, "diagnostics": len(diags),
              "version": program._version})
    enforce(diags, level, where)   # raises before caching on error
    cache[key] = len(diags)
    return diags
