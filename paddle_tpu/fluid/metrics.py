"""Python-side metrics (reference: python/paddle/fluid/metrics.py —
MetricBase, CompositeMetric, Precision, Recall, Accuracy, ChunkEvaluator,
EditDistance, Auc, DetectionMAP)."""
from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
           "ChunkEvaluator", "EditDistance", "Auc", "DetectionMAP"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k in list(self.__dict__):
            if not k.startswith("_"):
                v = self.__dict__[k]
                if isinstance(v, (int,)):
                    self.__dict__[k] = 0
                elif isinstance(v, float):
                    self.__dict__[k] = 0.0

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no data updated into Accuracy metric")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        for p, l in zip(preds, labels):
            if p == 1:
                if l == 1:
                    self.tp += 1
                else:
                    self.fp += 1

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        for p, l in zip(preds, labels):
            if l == 1:
                if p == 1:
                    self.tp += 1
                else:
                    self.fn += 1

    def eval(self):
        rec = self.tp + self.fn
        return float(self.tp) / rec if rec else 0.0


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        for i, l in enumerate(labels):
            b = min(int(preds[i, 1] * self._num_thresholds),
                    self._num_thresholds)
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        from ..utils.metrics import auc_from_histograms
        return auc_from_histograms(self._stat_pos, self._stat_neg)


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).reshape(-1)[0])

    def eval(self):
        p = self.num_correct_chunks / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        r = self.num_correct_chunks / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1 = 2 * p * r / (p + r) if self.num_correct_chunks else 0.0
        return p, r, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances != 0))

    def eval(self):
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class DetectionMAP:
    def __init__(self, *a, **k):
        raise NotImplementedError("DetectionMAP: detection batch pending")
