"""Parameter-to-pserver placement (reference:
python/paddle/fluid/transpiler/ps_dispatcher.py — RoundRobin:57,
HashName:31). Whole-parameter placement: the reference optionally slices
big params into blocks (slice_var_up); on the TPU build the dense path
never goes through the PS plane, so whole-param round-robin keeps the
sparse/host path simple."""
from __future__ import annotations


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class HashName(PSDispatcher):
    """hash(varname) % #pservers."""

    def dispatch(self, varlist):
        return [self._eps[abs(hash(v.name if hasattr(v, "name") else v))
                          % len(self._eps)] for v in varlist]


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        out = []
        for _v in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out
