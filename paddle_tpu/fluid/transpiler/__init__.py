"""Distributed transpilers (reference: python/paddle/fluid/transpiler/)."""
from .distribute_transpiler import (DistributeTranspiler,
                                    DistributeTranspilerConfig)
from .ps_dispatcher import HashName, RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "HashName", "RoundRobin", "memory_optimize", "release_memory"]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """No-op: XLA owns memory planning on TPU (reference transpiler/
    memory_optimization_transpiler.py is likewise deprecated)."""


def release_memory(input_program, skip_opt_set=None):
    """No-op: see memory_optimize."""
