"""Collective program transpilers (reference:
python/paddle/fluid/transpiler/collective.py — Collective:36,
GradAllReduce:178 `_insert_allreduce_ops`:209, LocalSGD:270).

Rewrites a single-process training program for multi-worker collective
training by inserting c_* ops. On TPU the FAST path is mesh sharding
(parallel/ — XLA inserts the collectives); this transpiler exists for
wire-level parity so reference-style transpiled programs still build and
execute: ring_id maps to a named mesh axis, c_allreduce_sum to lax.psum
(ops/collective_ops.py), and on a single chip the collectives are
identities."""
from __future__ import annotations

from ..backward import OP_ROLE_OPTIMIZE

OP_ROLE_KEY = "op_role"


class Collective:
    """Base (reference transpiler/collective.py:36)."""

    def __init__(self, nrings=1):
        self.nrings = nrings
        self.endpoints = None
        self.current_endpoint = None
        self.rank = 0
        self.nranks = 1

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        self.startup_program = startup_program
        self.main_program = main_program
        self.rank = rank
        self.endpoints = (endpoints.split(",")
                          if isinstance(endpoints, str) else list(endpoints))
        self.current_endpoint = current_endpoint
        self.nranks = len(self.endpoints)
        self._transpile_startup_program()
        self._transpile_main_program()
        return self

    # ------------------------------------------------------------------
    def _transpile_startup_program(self):
        block = self.startup_program.global_block()
        for ring_id in range(self.nrings):
            block.append_op(
                type="c_comm_init_all", inputs={}, outputs={},
                attrs={"ring_id": ring_id, "devices": [],
                       "rank": self.rank, "nranks": self.nranks,
                       "endpoints": self.endpoints})

    def _transpile_main_program(self):
        raise NotImplementedError

    def _insert_allreduce(self, block, idx, var_name, ring_id):
        block._insert_op(
            idx, type="c_allreduce_sum",
            inputs={"X": [var_name]}, outputs={"Out": [var_name]},
            attrs={"ring_id": ring_id, "use_calc_stream": True,
                   OP_ROLE_KEY: 1})


class GradAllReduce(Collective):
    """Sum-allreduce every grad before its optimizer op, scale by 1/nranks
    (reference :178)."""

    def __init__(self, nrings=1):
        super().__init__(nrings)

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        ring = 0
        # find (grad var, first optimizer-op index) pairs
        grads = []
        for i, op in enumerate(block.ops):
            if op.attrs.get(OP_ROLE_KEY) == OP_ROLE_OPTIMIZE and \
                    op.attrs.get("op_role_var"):
                grads.append((op.attrs["op_role_var"][1], i))
        inserted = 0
        for g, i in grads:
            idx = i + inserted
            block._insert_op(
                idx, type="scale", inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"scale": 1.0 / self.nranks, OP_ROLE_KEY: 1})
            self._insert_allreduce(block, idx, g, ring)
            inserted += 2
            ring = (ring + 1) % self.nrings


class LocalSGD(Collective):
    """Periodic parameter averaging instead of per-step grad allreduce
    (reference :270): params snapshot before optimize, delta averaged
    across workers every step (the reference's k_steps pacing is driven by
    the caller)."""

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        params = []
        for op in block.ops:
            if op.attrs.get(OP_ROLE_KEY) == OP_ROLE_OPTIMIZE and \
                    op.attrs.get("op_role_var"):
                params.append(op.attrs["op_role_var"][0])
        ring = 0
        for p in dict.fromkeys(params):
            block.append_op(
                type="scale", inputs={"X": [p]}, outputs={"Out": [p]},
                attrs={"scale": 1.0 / self.nranks, OP_ROLE_KEY: 2})
            block.append_op(
                type="c_allreduce_sum", inputs={"X": [p]},
                outputs={"Out": [p]},
                attrs={"ring_id": ring, "use_calc_stream": True,
                       OP_ROLE_KEY: 2})
            ring = (ring + 1) % self.nrings
