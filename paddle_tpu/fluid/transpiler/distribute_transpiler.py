"""DistributeTranspiler — rewrites a training Program into trainer and
pserver Programs (reference: python/paddle/fluid/transpiler/
distribute_transpiler.py — transpile:540, get_trainer_program:1011,
get_pserver_program:1146, get_startup_program:1448).

Behavioral parity, TPU framing: the trainer program keeps forward+backward
(compiled to XLA where pure) and ends in send/send_barrier/recv/
fetch_barrier host ops; the pserver program is one listen_and_serv op whose
optimize sub-blocks are the original optimizer ops, applied after summing
each grad across trainers (sync) or on arrival (async). Parameters are
placed whole, round-robin (reference's slice_var_up block-splitting is a
bandwidth optimization for GPU clusters; the TPU dense path uses ICI
collectives instead, so the PS plane only carries the sparse/host-table
configs). ``is_distributed`` embeddings are rewritten to
distributed_lookup_table pulls with sparse push-grads served row-wise.
"""
from __future__ import annotations

import warnings
from typing import Dict, List, Optional

from ..framework import (Program, default_main_program,
                         default_startup_program)
from ..backward import OP_ROLE_OPTIMIZE
from .ps_dispatcher import RoundRobin


class DistributeTranspilerConfig:
    """reference: transpiler/distribute_transpiler.py:154."""
    slice_var_up = False          # whole-param placement (see module doc)
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    mode = "pserver"
    print_log = False
    wait_port = True
    runtime_split_send_recv = False
    sync_mode = True
    # GEO-SGD async mode (reference: geo_sgd_transpiler.py +
    # GeoSgdCommunicator communicator.h:383): train locally, push param
    # DELTAS to the pservers every geo_sgd_need_push_nums steps
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100
    # memory bound for LazyEmbeddingTable-hosted sparse tables (rows kept
    # per pserver before LRU eviction); 0 = unbounded
    sparse_table_max_rows = 0
    # async overlap plane (docs/PS_DATA_PLANE.md "Async overlap"): the
    # sync trainer's send/send_barrier/recv/fetch_barrier tail collapses
    # into ONE ps_round op whose kernel pipelines the round behind the
    # next step's compute, bounded by FLAGS_async_staleness
    # (0 = the round runs inline, bit-identical to the plain sync tail).
    # Also turned on implicitly when FLAGS_async_staleness > 0 at
    # transpile time, so subprocess trainers enable it via env.
    async_overlap = False


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()

    # ------------------------------------------------------------------
    def transpile(self, trainer_id: int, program: Optional[Program] = None,
                  pservers: str = "127.0.0.1:6174", trainers: int = 1,
                  sync_mode: bool = True,
                  startup_program: Optional[Program] = None,
                  current_endpoint: str = ""):
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program or default_main_program()
        self.origin_startup = startup_program or default_startup_program()
        self.pserver_endpoints = [ep.strip() for ep in pservers.split(",")
                                  if ep.strip()]

        # elastic membership (docs/FAULT_TOLERANCE.md "Elastic
        # membership"): the static shard map becomes an epoch-stamped
        # ClusterView. Programs keep these SLOT endpoints in their op
        # attrs forever; the RPC layer resolves a slot to whichever
        # server currently owns it, so a drain/rejoin/failover never
        # touches a transpiled program. Installing the epoch-0 view here
        # seeds every process (trainer and pserver both transpile).
        from .. import ps_membership
        self.cluster_view = ps_membership.ClusterView.initial(
            self.pserver_endpoints)
        # A DIFFERENT slot set means a NEW cluster, not a membership
        # change of the current one (slots are immutable epoch-0 names;
        # drains/failovers remap owners, never the slot list). Without
        # the reset, a long-lived process that trains job 2 after job 1
        # — with an ephemeral port reused across the two pserver lists —
        # would resolve job 2's slot through job 1's high-epoch view to
        # a dead endpoint, and the monotonic install could never seed
        # job 2's epoch-0 view over it.
        cur = ps_membership.current_view()
        if cur is not None and \
                set(cur.slots) != set(self.pserver_endpoints):
            ps_membership.reset_views()
        ps_membership.install_view(self.cluster_view)

        # 1. discover (param, grad, optimize op) triples
        self.param_grad_ops = []     # (param_name, grad_name, op)
        block = self.origin_program.global_block()
        for op in block.ops:
            if op.attrs.get("op_role") == OP_ROLE_OPTIMIZE and \
                    op.attrs.get("op_role_var"):
                p, g = op.attrs["op_role_var"][:2]
                self.param_grad_ops.append((p, g, op))
        if not self.param_grad_ops:
            raise ValueError("transpile: no optimizer ops found — call "
                             "optimizer.minimize(loss) first")

        # 2. identify distributed sparse tables (is_distributed lookups)
        self.sparse_tables = set()
        for op in block.ops:
            if op.type in ("lookup_table", "lookup_table_v2") and \
                    op.attrs.get("is_distributed"):
                self.sparse_tables.add(op.input("W")[0])

        # 2b. beyond-HBM sparse tables (reference fleet_wrapper.h:86-190
        # DownpourSparseTable): above the threshold the table is hosted as
        # an init-on-touch LazyEmbeddingTable on every pserver (row-sharded
        # by id) and must NEVER materialize on a trainer — rewrite its
        # trainer-startup init to fake_init and keep a pristine startup
        # for the pserver side
        import numpy as _np
        from .. import core as _core
        thresh = int(_core.globals_["FLAGS_lazy_sparse_table_threshold"])
        self.lazy_tables: Dict[str, tuple] = {}
        for w in self.sparse_tables:
            v = block.vars.get(w)
            shape = tuple(getattr(v, "shape", ()) or ())
            if shape and int(_np.prod(shape)) >= thresh:
                self.lazy_tables[w] = (int(shape[0]),
                                       int(_np.prod(shape[1:])))
        self._startup_src = (self.origin_startup.clone()
                            if self.lazy_tables else self.origin_startup)
        if self.lazy_tables:
            from ..core import _STR_TO_DTYPE
            sblock = self.origin_startup.global_block()
            for op in list(sblock.ops):
                hit = [n for n in op.output_arg_names
                       if n in self.lazy_tables]
                if not hit:
                    continue
                others = [n for n in op.output_arg_names if n not in hit]
                if others:
                    # a multi-output init also feeding non-lazy vars must
                    # keep initializing them — only a single-output init
                    # op can be rewritten in place
                    raise NotImplementedError(
                        f"startup op '{op.type}' initializes lazy table "
                        f"{hit} together with {others}; split the "
                        "initializers")
                w = hit[0]
                _h, d = self.lazy_tables[w]
                sv = block.vars.get(w)
                dt = getattr(sv, "dtype", None)
                if isinstance(dt, str):
                    dt = _STR_TO_DTYPE.get(dt, 5)
                op.type = "fake_init"
                op.inputs = {}
                op.outputs = {"Out": [w]}
                op.attrs = {"shape": [1, d],
                            "dtype": int(dt) if dt is not None else 5}

        # 3. place params on pservers
        dispatcher = RoundRobin(self.pserver_endpoints)
        names = [p for p, _, _ in self.param_grad_ops]
        eps = dispatcher.dispatch(names)
        self.param_ep: Dict[str, str] = dict(zip(names, eps))
        self.grad_of: Dict[str, str] = {p: g for p, g, _ in
                                        self.param_grad_ops}

        if self.config.geo_sgd_mode:
            self._build_geo_trainer_program()
        else:
            self._build_trainer_program()
        # static-analysis choke point (docs/ANALYSIS.md): the transpiler
        # verifies its OWN output — the distributed-protocol rules
        # (barrier pairing, sparse-grad rewrite completeness, ps_round
        # tail vs FLAGS_async_staleness) exist because transpiler bugs of
        # exactly these classes shipped before (the PR 4 silent LOCAL
        # lookup_table_grad). Fetch list unknown here, so dead-code rules
        # skip; gated on FLAGS_program_verify like every choke point.
        from .. import analysis
        analysis.maybe_verify(self.trainer_program, "transpiler")
        return self

    # ------------------------------------------------------------------
    def _build_geo_trainer_program(self):
        """GEO: keep the local optimizer ops; append one geo_sgd_send op
        that every N steps pushes (param - snapshot) deltas to each param's
        pserver and pulls the merged global params back (reference:
        geo_sgd_transpiler.py builds the local program;
        GeoSgdCommunicator does the delta sync)."""
        prog = self.origin_program.clone()
        block = prog.global_block()
        dense = [p for p, _, _ in self.param_grad_ops
                 if p not in self.sparse_tables]
        sparse = [p for p, _, _ in self.param_grad_ops
                  if p in self.sparse_tables]
        if any(p in getattr(self, "lazy_tables", {}) for p in sparse):
            raise NotImplementedError(
                "geo_sgd_mode keeps a local optimizer, so beyond-HBM "
                "lazy sparse tables can't train GEO — use sync/async "
                "PS mode for tables above "
                "FLAGS_lazy_sparse_table_threshold")
        # sparse tables delta-sync row-wise (reference GeoSgdCommunicator
        # SendUpdateSparseVars); in GEO mode the local optimizer keeps
        # the table in trainer scope, so lookups stay LOCAL
        block.append_op(
            type="geo_sgd_send",
            inputs={"Params": dense, "SparseParams": sparse}, outputs={},
            attrs={"epmap": [self.param_ep[p] for p in dense + sparse],
                   "push_nums": int(self.config.geo_sgd_need_push_nums),
                   "trainer_id": self.trainer_id,
                   "trainers": self.trainer_num})
        self.trainer_program = prog

    # ------------------------------------------------------------------
    def _build_trainer_program(self):
        prog = self.origin_program.clone()
        block = prog.global_block()
        # drop optimizer ops — updates happen on the pservers
        keep = [op for op in block.ops
                if not (op.attrs.get("op_role") == OP_ROLE_OPTIMIZE
                        and op.attrs.get("op_role_var"))]
        # rewrite distributed embeddings to remote pulls, and their grad
        # ops to remote row pushes
        for op in keep:
            if op.type in ("lookup_table", "lookup_table_v2") and \
                    op.input("W")[0] in self.sparse_tables:
                w = op.input("W")[0]
                op.type = "distributed_lookup_table"
                op.inputs = {"Ids": op.input("Ids"), "W": [w]}
                op.outputs = {"Outputs": op.output("Out")}
                op.attrs.update({
                    "table_names": [w],
                    # row-sharded across every pserver (id % n_eps), the
                    # reference's table-section split; each pserver holds
                    # its id-subset (lazily for beyond-HBM tables)
                    "epmap": list(self.pserver_endpoints),
                    "trainer_id": self.trainer_id})
            elif op.type in ("lookup_table_grad", "lookup_table_v2_grad") \
                    and op.input("W")[0] in self.sparse_tables:
                # the table lives on the pservers, so its row gradient
                # must CROSS THE WIRE (distributed_lookup_table_grad:
                # duplicate-premerged, row-sharded sends — the reference
                # transpiler's sparse-grad send rewrite). Leaving the
                # local lookup_table_grad here would drop the sparse
                # update on the trainer floor: the embedding would never
                # train.
                w = op.input("W")[0]
                op.type = "distributed_lookup_table_grad"
                op.inputs = {"Ids": op.input("Ids"), "W": [w],
                             "Outputs@GRAD": op.input("Out@GRAD")}
                op.outputs = {}
                op.attrs.update({
                    "table_names": [w],
                    "epmap": list(self.pserver_endpoints),
                    "trainer_id": self.trainer_id})
        block.ops[:] = keep

        # group dense sends/recvs per endpoint
        by_ep_grads: Dict[str, List[str]] = {}
        by_ep_params: Dict[str, List[str]] = {}
        for p, g, _op in self.param_grad_ops:
            if p in self.sparse_tables:
                continue  # sparse grads ride distributed_lookup_table_grad
            ep = self.param_ep[p]
            by_ep_grads.setdefault(ep, []).append(g)
            by_ep_params.setdefault(ep, []).append(p)
        eps = sorted(by_ep_grads)
        # barriers go to EVERY pserver, not just the ones hosting dense
        # grads: a sparse-only shard defers its row applies to the send-
        # barrier release (listen_and_serv sync mode) and would never
        # train if no barrier reached it
        barrier_eps = list(self.pserver_endpoints)
        from .. import core as _core
        if self.sync_mode and (
                self.config.async_overlap
                or int(_core.globals_["FLAGS_async_staleness"]) > 0):
            # async-mode rewrite (docs/PS_DATA_PLANE.md "Async
            # overlap"): the whole comm tail becomes ONE ps_round op —
            # grads/params flattened in the same sorted-endpoint order
            # the per-ep send/recv ops would have used, barriers to
            # every pserver as above. The op's kernel replays exactly
            # this sequence inline at FLAGS_async_staleness=0 and
            # pipelines it behind the next step's compute at
            # staleness>0.
            grads = [g for ep in eps for g in by_ep_grads[ep]]
            gmap = [ep for ep in eps for _ in by_ep_grads[ep]]
            params = [p for ep in eps for p in by_ep_params[ep]]
            pmap = [ep for ep in eps for _ in by_ep_params[ep]]
            block.append_op(
                type="ps_round", inputs={"X": grads},
                outputs={"Out": params},
                attrs={"grad_epmap": gmap, "param_epmap": pmap,
                       "endpoints": barrier_eps,
                       "trainer_id": self.trainer_id})
            self.trainer_program = prog
            return
        for ep in eps:
            block.append_op(
                type="send", inputs={"X": by_ep_grads[ep]}, outputs={},
                attrs={"epmap": [ep] * len(by_ep_grads[ep]),
                       "trainer_id": self.trainer_id})
        if self.sync_mode:
            block.append_op(type="send_barrier", inputs={}, outputs={},
                            attrs={"endpoints": barrier_eps,
                                   "trainer_id": self.trainer_id})
        for ep in eps:
            block.append_op(
                type="recv", inputs={},
                outputs={"Out": by_ep_params[ep]},
                attrs={"epmap": [ep] * len(by_ep_params[ep]),
                       "trainer_id": self.trainer_id})
        if self.sync_mode:
            block.append_op(type="fetch_barrier", inputs={}, outputs={},
                            attrs={"endpoints": barrier_eps,
                                   "trainer_id": self.trainer_id})
        self.trainer_program = prog

    # ------------------------------------------------------------------
    def get_trainer_program(self, wait_port: bool = True) -> Program:
        return self.trainer_program

    def get_pserver_program(self, endpoint: str, bind_endpoint: str = "",
                            standby: bool = False,
                            replica_of: str = "") -> Program:
        """Pserver program for slot ``endpoint``. The elastic-membership
        kwargs build the program for a process serving that slot from
        ANOTHER address: ``bind_endpoint`` is where it actually listens,
        ``standby`` starts it as a warm drain/rejoin destination, and
        ``replica_of`` additionally makes it a failover replica that
        applies the primary's chain-forwarded updates and promotes
        itself when the primary dies (FLAGS_ps_replicas=2)."""
        prog = Program()
        gblock = prog.global_block()
        origin_block = self.origin_program.global_block()
        member_attrs = {
            "pserver_endpoints": list(self.pserver_endpoints),
            "bind_endpoint": str(bind_endpoint or ""),
            "standby": bool(standby),
            "replica_of": str(replica_of or ""),
        }

        # sparse tables are row-sharded: EVERY pserver hosts its id-subset
        mine = [(p, g, op) for p, g, op in self.param_grad_ops
                if self.param_ep[p] == endpoint or p in self.sparse_tables]

        if self.config.geo_sgd_mode:
            # GEO pserver: hosts the params, applies pushed deltas on
            # arrival, serves pulls — no optimize blocks (the optimizer
            # ran on the trainers)
            for p, _g, _op in mine:
                src = origin_block.vars.get(p)
                gblock.create_var(name=p, shape=getattr(src, "shape", None),
                                  dtype=getattr(src, "dtype", None),
                                  persistable=True)
            gblock.append_op(
                type="listen_and_serv", inputs={}, outputs={},
                attrs={"endpoint": endpoint, "sync_mode": False,
                       "Fanin": self.trainer_num, "optimize_blocks": [],
                       "grad_to_block_id": [], "distributed_mode": 2,
                       **member_attrs})
            prog._ps_endpoint = endpoint
            prog._pserver_params = [p for p, _, _ in mine]
            return prog
        optimize_blocks = []
        grad_to_block_id = []
        needed_vars = set()
        for i, (p, g, op) in enumerate(mine):
            blk = prog._create_block(parent_idx=0)
            blk.append_op(type=op.type,
                          inputs={k: list(v) for k, v in op.inputs.items()},
                          outputs={k: list(v) for k, v in op.outputs.items()},
                          attrs={k: v for k, v in op.attrs.items()
                                 if k != "op_role"})
            prog._rollback()
            optimize_blocks.append(blk)
            if p not in self.sparse_tables:
                grad_to_block_id.append(f"{g}:{i}")
            needed_vars.update(op.input_arg_names)
            needed_vars.update(op.output_arg_names)
        for name in sorted(needed_vars):
            src = origin_block.vars.get(name)
            if src is not None:
                gblock.create_var(name=name, shape=src.shape,
                                  dtype=src.dtype, persistable=True)
            else:
                gblock.create_var(name=name, persistable=True)
        gblock.append_op(
            type="listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": endpoint, "sync_mode": self.sync_mode,
                   "Fanin": self.trainer_num,
                   "optimize_blocks": optimize_blocks,
                   "grad_to_block_id": grad_to_block_id,
                   "distributed_mode": 0 if self.sync_mode else 1,
                   **member_attrs})
        prog._ps_endpoint = endpoint
        prog._pserver_params = [p for p, _, _ in mine]
        return prog

    def get_startup_program(self, endpoint: str,
                            pserver_program: Optional[Program] = None,
                            startup_program: Optional[Program] = None
                            ) -> Program:
        """Init program for one pserver: the original init ops of every var
        the pserver hosts (params, accumulators, lr). Beyond-threshold
        sparse tables initialize as LazyEmbeddingTable (init-on-touch)
        instead of running their dense initializer."""
        src = startup_program or getattr(self, "_startup_src",
                                         self.origin_startup)
        hosted = set()
        if pserver_program is not None:
            hosted.update(v for v in pserver_program.global_block().vars)
        else:
            hosted.update(p for p, ep in self.param_ep.items()
                          if ep == endpoint)
            hosted.update(getattr(self, "lazy_tables", {}))
        prog = Program()
        block = prog.global_block()
        lazy = getattr(self, "lazy_tables", {})
        emitted_lazy = set()
        for op in src.global_block().ops:
            outs = set(op.output_arg_names)
            if not (outs & hosted):
                continue
            hit = [n for n in outs if n in lazy]
            if hit:
                w = hit[0]
                if w not in emitted_lazy:
                    emitted_lazy.add(w)
                    h, d = lazy[w]
                    # carry the model-declared initializer into the lazy
                    # table where representable (row init is
                    # uniform(±scale)): a symmetric uniform_random maps
                    # exactly; other families fall back to the
                    # ±1/sqrt(dim) default with a warning (ADVICE r2)
                    seed = int(op.attrs.get("seed") or 0)
                    scale = 0.0
                    if op.type == "uniform_random":
                        mn = float(op.attrs.get("min", -1.0))
                        mx = float(op.attrs.get("max", 1.0))
                        if mx > 0 and abs(mn + mx) <= 1e-9 * mx:
                            scale = mx
                        else:
                            warnings.warn(
                                f"lazy table {w}: asymmetric "
                                f"uniform_random({mn}, {mx}) is not "
                                "representable by the row init; using "
                                "uniform(±1/sqrt(dim))")
                    else:
                        warnings.warn(
                            f"lazy table {w}: initializer '{op.type}' is "
                            "not representable by the row init; using "
                            "uniform(±1/sqrt(dim))")
                    block.create_var(name=w, persistable=True)
                    block.append_op(
                        type="lazy_table_init", inputs={},
                        outputs={"Out": [w]},
                        attrs={"height": h, "dim": d, "seed": seed,
                               "scale": scale,
                               "max_rows": int(getattr(
                                   self.config,
                                   "sparse_table_max_rows", 0))})
                continue
            for name in outs:
                sv = src.global_block().vars.get(name)
                if sv is not None and name not in block.vars:
                    block.create_var(name=name, shape=sv.shape,
                                     dtype=sv.dtype, persistable=True)
            block.append_op(
                type=op.type,
                inputs={k: list(v) for k, v in op.inputs.items()},
                outputs={k: list(v) for k, v in op.outputs.items()},
                attrs=dict(op.attrs))
        return prog
