"""fluid.nets — convenience composites over fluid.layers (reference:
python/paddle/fluid/nets.py — simple_img_conv_pool, img_conv_group,
sequence_conv_pool, glu, scaled_dot_product_attention)."""
from __future__ import annotations

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "glu", "scaled_dot_product_attention"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    """conv2d + pool2d (reference nets.py simple_img_conv_pool)."""
    conv_out = layers.conv2d(input, num_filters=num_filters,
                             filter_size=filter_size, stride=conv_stride,
                             padding=conv_padding, dilation=conv_dilation,
                             groups=conv_groups, param_attr=param_attr,
                             bias_attr=bias_attr, act=act)
    return layers.pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """VGG-style conv(+bn+dropout) group ending in one pool (reference
    nets.py img_conv_group)."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _expand(v):
        return [v] * len(conv_num_filter) if not isinstance(
            v, (list, tuple)) else list(v)

    conv_padding = _expand(conv_padding)
    conv_filter_size = _expand(conv_filter_size)
    param_attr = _expand(param_attr)
    conv_with_batchnorm = _expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _expand(conv_batchnorm_drop_rate)

    for i, nf in enumerate(conv_num_filter):
        local_act = None if conv_with_batchnorm[i] else conv_act
        tmp = layers.conv2d(tmp, num_filters=nf,
                            filter_size=conv_filter_size[i],
                            padding=conv_padding[i],
                            param_attr=param_attr[i], act=local_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = layers.dropout(tmp,
                                     dropout_prob=conv_batchnorm_drop_rate[i])
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None):
    """sequence_conv + sequence_pool (reference nets.py
    sequence_conv_pool; LoD-aware — text-conv models)."""
    conv_out = layers.sequence_conv(input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act,
                                    bias_attr=bias_attr)
    return layers.sequence_pool(conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    """Gated linear unit: split last/dim axis in two, a * sigmoid(b)
    (reference nets.py glu)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention over [B, S, D] tensors
    (reference nets.py scaled_dot_product_attention). On TPU the whole
    expression fuses into the jitted step; the Pallas flash-attention path
    serves the fused multihead_matmul op."""
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("queries and keys must have the same hidden size")
    if keys.shape[-2] != values.shape[-2] if len(keys.shape) > 2 else False:
        raise ValueError("keys and values must share the sequence length")
    if queries.shape[-1] % num_heads != 0:
        raise ValueError("hidden size must divide num_heads")

    def _split_heads(x):
        if num_heads == 1:
            return x
        h = x.shape[-1] // num_heads
        x = layers.reshape(x, [0, 0, num_heads, h])
        return layers.transpose(x, [0, 2, 1, 3])

    def _merge_heads(x):
        if num_heads == 1:
            return x
        x = layers.transpose(x, [0, 2, 1, 3])
        return layers.reshape(x, [0, 0, int(x.shape[2]) * int(x.shape[3])])

    q, k, v = _split_heads(queries), _split_heads(keys), _split_heads(values)
    key_dim = int(queries.shape[-1]) // num_heads
    scaled_q = layers.scale(q, scale=key_dim ** -0.5)
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    return _merge_heads(layers.matmul(weights, v))
