"""Executor — runs Programs on TPU.

The reference Executor (reference: paddle/fluid/framework/executor.cc:184,
python/paddle/fluid/executor.py:457) interprets a block op-by-op per step,
doing per-op kernel choice, data transform, InferShape and GC. That design
is inverted here for TPU: ``Executor.run`` traces the whole block ONCE into
a pure function ``(state, feeds, rng) -> (fetches, new_state)`` and compiles
it with ``jax.jit`` — op fusion, layout, memory planning and GC all become
XLA's job, and parameter updates alias in-place via buffer donation.

Three paths:
  * compiled (default): pure-traceable blocks. Program cache keyed like the
    reference's (executor.py:1171 cache) by (program id, version, feeds,
    fetches, scope).
  * segmented (default when the block is NOT fully traceable): the op list
    is partitioned into maximal pure runs — each jitted as its own donated
    computation — around stateful/host-op *islands* the interpreter
    dispatches eagerly (``_SegmentedBlock``; analysis in
    fluid/ir.py:analyze_block_segments). One auc/print/read op no longer
    de-compiles the whole block: the reference pays per-op dispatch
    everywhere (executor.cc:469-475), this build pays it only at islands.
  * interpreted: the correctness oracle, also used for startup programs and
    blocks with nothing worth jitting (FLAGS_executor_segmentation=False
    forces it for all partially-stateful blocks). Still executes on
    device, just eagerly.

Feed/fetch: direct dict-in/list-out like the reference API; programs that
already contain feed/fetch ops (e.g. deserialized reference models) work
too — their feed/fetch ops read/write the same feed/fetch list variables
(reference: executor.cc:195-306).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import os
import weakref

import numpy as np
import jax
import jax.numpy as jnp

from . import core
from . import telemetry as _telemetry
from . import analysis as _analysis
from .core import LoDTensor, Scope, global_scope
from .framework import Program, Variable, default_main_program
from ..ops.registry import (OPS, run_generic_grad, GRAD_SUFFIX,
                            resolve_base_info as _resolve_base_info)

__all__ = ["Executor", "global_scope", "scope_guard", "FetchHandler"]


class FetchHandler:
    """Periodic async fetch during dataset training (reference:
    executor.py FetchHandler + trainer FetchHandlerMonitor thread — user
    overrides handler(); it receives {var_name: numpy|None} snapshots every
    ``period_secs`` while train_from_dataset runs)."""

    def __init__(self, var_dict=None, period_secs=60):
        if var_dict is None or not isinstance(var_dict, dict):
            raise TypeError("var_dict must be a {name: Variable} dict")
        self.var_dict = var_dict
        self.period_secs = period_secs

    def handler(self, res_dict):
        for key in res_dict:
            if isinstance(res_dict[key], np.ndarray):
                print(f"{key}[0]: {res_dict[key][0]} ")

    @staticmethod
    def help():
        print("""
class FetchHandlerExample(FetchHandler):
    def handler(self, res_dict):
        print(res_dict["var1"])  # numpy snapshot (None if not yet set)
handler = FetchHandlerExample(var_dict={"var1": var1}, period_secs=60)
""")


class _FetchHandlerMonitor:
    """Daemon thread sampling scope vars for a FetchHandler (reference:
    trainer_factory.py FetchHandlerMonitor)."""

    def __init__(self, scope: Scope, handler: FetchHandler):
        import threading
        self._scope = scope
        self._handler = handler
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _sample(self):
        res = {}
        for name, var in self._handler.var_dict.items():
            vname = getattr(var, "name", var)
            v = self._scope.find_var(vname)
            if v is None or not v.is_initialized():
                res[name] = None
                continue
            try:
                res[name] = np.asarray(v.get_tensor().array)
            except (RuntimeError, TypeError):
                # RuntimeError: donated state buffer invalidated between
                # the scope read and the host copy (the training step
                # aliases it in place). TypeError: non-LoDTensor holder
                # (e.g. SelectedRows) has no dense tensor view.
                # Monitoring is best-effort — report None.
                res[name] = None
        return res

    def _loop(self):
        while not self._stop_evt.wait(self._handler.period_secs):
            self._handler.handler(self._sample())

    def start(self):
        self._thread.start()

    def stop(self):
        # stop the periodic loop and join BEFORE the final synchronous
        # sample, so the user handler is never invoked concurrently with
        # (or after) it
        self._stop_evt.set()
        if self._thread.is_alive():
            # unbounded: the loop exits as soon as any in-flight handler
            # call returns (the event is already set), and joining fully is
            # what guarantees no concurrent handler invocation below
            self._thread.join()
        # final synchronous sample so short runs still see one callback
        self._handler.handler(self._sample())


import contextlib


@contextlib.contextmanager
def scope_guard(scope: Scope):
    old = core._switch_scope(scope)
    try:
        yield
    finally:
        core._switch_scope(old)


class ExecContext:
    """Handed to stateful kernels via attrs['_ctx']."""
    __slots__ = ("scope", "executor", "op", "place", "rng_base")

    def __init__(self, scope, executor, op, place, rng_base):
        self.scope = scope
        self.executor = executor
        self.op = op
        self.place = place
        self.rng_base = rng_base


def _as_lodtensor(data, place) -> LoDTensor:
    if isinstance(data, LoDTensor):
        if not isinstance(data.array, jax.Array):
            data.set(np.asarray(data.array), place)
        return data
    if isinstance(data, jax.Array):
        # already device-resident (e.g. the DataLoader window prefetch
        # stage device_put the batch while the previous window computed)
        # — wrap without a host round-trip, nothing to re-upload
        return LoDTensor(data)
    t = LoDTensor()
    t.set(data if isinstance(data, np.ndarray) else np.asarray(data), place)
    return t


def _initialized_tensor(scope, name) -> Optional[LoDTensor]:
    """The scope var's holder when it exists and is an initialized dense
    LoDTensor; None otherwise. THE numeric-fault-plane state predicate:
    the compiled guard classification (_CompiledBlock._init_guard) and
    the interpreter oracle (_interp_guard_cfg/_run_interpreted_step)
    must agree on it or their health/select variable sets drift and the
    bit-parity contract breaks."""
    v = scope.find_var(name)
    if v is not None and v.is_initialized() and isinstance(v.value(),
                                                           LoDTensor):
        return v.value()
    return None


def _window_feed_names(program, feed, n_steps) -> Tuple[str, ...]:
    """Feeds carrying a leading window dimension: value rank is the
    program var's rank + 1 and the leading dim equals ``n_steps`` —
    `feed={x: [K, batch, ...]}` with `n_steps=K` means the K slices are
    K *distinct* batches, consumed one per step (lax.scan xs on the
    compiled path). A rank-matched feed whose leading dim disagrees
    with n_steps is a user error and raises; LoD cannot describe a
    stacked window, so a windowed feed with LoD raises too."""
    names = []
    block = program.global_block()
    for name, data in feed.items():
        arr = data.array if isinstance(data, LoDTensor) else data
        shp = getattr(arr, "shape", None)
        if not shp:
            continue
        v = block._find_var_recursive(name)
        vshape = getattr(v, "shape", None) if v is not None else None
        if vshape is None or len(shp) != len(vshape) + 1:
            continue
        # only batch-majored vars (first dim -1, the fluid.data shape)
        # are unambiguous: a normal feed has exactly the var's rank, so
        # rank+1 can only mean a leading window dim. Vars declared with
        # a concrete full shape (raw create_var) commonly take feeds of
        # any rank through rank-polymorphic kernels — never windowed.
        if vshape[0] != -1:
            continue
        if shp[0] != n_steps:
            if n_steps == 1:
                # a plain run may legitimately feed extra-rank data to
                # rank-polymorphic ops — only an explicit multi-step
                # request makes the mismatch a user error
                continue
            raise ValueError(
                f"feed '{name}' has shape {tuple(shp)} — rank says it "
                f"carries a leading window dimension (program var rank "
                f"{len(vshape)}), but the window length {shp[0]} does not "
                f"match n_steps={n_steps}")
        if isinstance(data, LoDTensor) and data.lod():
            raise NotImplementedError(
                f"windowed feed '{name}' carries LoD — one LoD cannot "
                f"describe K stacked batches; feed dense windows or run "
                f"per-step (n_steps=1)")
        names.append(name)
    return tuple(names)


def _op_reads_host_values(op) -> bool:
    """Ops whose kernels read input VALUES host-side (registry
    host_inputs) cannot take those values as traced jit arguments."""
    if OPS.has(op.type):
        return bool(OPS.get(op.type).host_inputs)
    if op.type.endswith("_grad") and OPS.has(op.type[:-5]):
        return bool(OPS.get(op.type[:-5]).host_inputs)
    return False


def _op_is_stateful(op) -> bool:
    info = _resolve_base_info(op.type)
    if info is None:
        return True  # unknown op: be safe, run eagerly (raises w/ context)
    return info.stateful


# control-flow ops the compiled path lowers to lax primitives instead of
# scope interpretation (see _CompiledBlock._exec_ops)
_LOWERED_CONTROL = frozenset({"while", "conditional_block",
                              "conditional_block_infer", "select_input"})


def _op_needs_rng(op_type: str) -> bool:
    info = _resolve_base_info(op_type)
    return info.needs_rng if info is not None else False


def _ops_compilable(ops, in_cond=False) -> bool:
    """True if every op either has a pure kernel or is control flow whose
    sub-blocks are themselves compilable. ``in_cond``: inside a
    conditional_block sub-block, where the compiled lowering traces BOTH
    branches and mask-merges — an rng op there would draw in the untaken
    branch too, so such programs route to the interpreter's
    single-branch semantics instead (reference
    conditional_block_op.cc executes only the taken branch)."""
    for op in ops:
        if op.type in ("feed", "fetch"):
            continue
        if op.type in _LOWERED_CONTROL:
            sub = op.attrs.get("sub_block")
            cond = in_cond or op.type.startswith("conditional_block")
            if sub is not None and not _ops_compilable(sub.ops, cond):
                return False
        elif _op_is_stateful(op) or _op_reads_host_values(op):
            return False
        elif in_cond and _op_needs_rng(op.type):
            return False
    return True


# ------------------------------------------------------------------ LoD
# LoD (variable-length sequence) metadata rides NEXT TO arrays as
# host-static nested tuples; under jit it is trace-time constant (the jit
# cache is keyed per feed-LoD bucket), so segment ids computed from it
# lower to XLA constants. Replaces the reference's per-step LoD InferShape
# (framework/lod_tensor.h:104, operator.cc:967).
def _normalize_lod(lod):
    if not lod:
        return None
    return tuple(tuple(int(x) for x in lvl) for lvl in lod)


def _op_needs_lod(op) -> bool:
    if OPS.has(op.type):
        return OPS.get(op.type).needs_lod
    if op.type.endswith("_grad") and OPS.has(op.type[:-5]):
        return OPS.get(op.type[:-5]).needs_lod
    return False


def _collect_in_lods(op, lookup):
    return {slot: [lookup(n) for n in names]
            for slot, names in op.inputs.items()}


def _propagate_lods(op, outs, in_lods, set_lod, get_len):
    """Apply kernel-declared output LoDs; else share the first lod-bearing
    input's LoD with outputs of matching leading length (reference ShareLoD
    default)."""
    explicit = None
    if isinstance(outs, dict):
        explicit = outs.pop("_lod", None)
    if explicit:
        for slot, levels_list in explicit.items():
            names = op.outputs.get(slot) or []
            for n, lv in zip(names, levels_list):
                set_lod(n, _normalize_lod(lv))
        return
    src = None
    for slot, lods in in_lods.items():
        for lv in lods:
            if lv:
                src = lv
                break
        if src:
            break
    if not src:
        return
    total = src[-1][-1]
    for slot, names in op.outputs.items():
        for n in names:
            if get_len(n) == total:
                set_lod(n, src)


def _classify_block_state(ops, block, feed_names, scope):
    """Classify a block's variables for a traced step: names read before
    any write that are initialized LoDTensors in the scope become *state*
    (threaded through the step and donated when overwritten); everything
    written (including sub-block writes) lands in *written*. Raises for
    data vars missing from the feed and for uninitialized persistables —
    the same contract for the fused and segmented compiled paths."""
    written: set = set()
    state_names: List[str] = []
    block_vars = block.vars
    for op in ops:
        for name in op.input_arg_names:
            if name in written or name in feed_names or name in state_names:
                continue
            bv = block_vars.get(name)
            if bv is not None and (bv.is_data or bv.need_check_feed):
                # a data var must come from the feed dict — pulling a
                # stale value from scope would silently compute on the
                # previous batch (reference: executor feed checks)
                raise KeyError(
                    f"feed variable '{name}' is required by the program "
                    f"but was not provided in feed=")
            v = scope.find_var(name)
            if v is not None and v.is_initialized() and isinstance(
                    v.value(), LoDTensor):
                state_names.append(name)
            elif bv is not None and bv.persistable:
                raise RuntimeError(
                    f"persistable variable '{name}' (read by op "
                    f"'{op.type}') is not initialized in the scope — "
                    f"run the startup program first")
        written.update(op.output_arg_names)
        sub = op.attrs.get("sub_block")
        if sub is not None:
            stack = [sub]
            while stack:
                b = stack.pop()
                for sop in b.ops:
                    written.update(sop.output_arg_names)
                    sb = sop.attrs.get("sub_block")
                    if sb is not None:
                        stack.append(sb)
    return state_names, written


_GUARD_ACTIONS = frozenset({"raise", "skip", "rollback"})


def _block_reads_amp_scale(ops, amp) -> bool:
    """True when the (feed/fetch-free) op list actually consumes the AMP
    loss-scaling var — i.e. the scaled-loss/unscale machinery survived
    into this program. A clone/prune that sliced it away (forward-only
    eval programs) must not run the scale epilogue: eval steps would
    silently inflate the shared training scale and counters."""
    name = amp["scale"]
    return any(name in op.input_arg_names for op in ops)


def _amp_scale_update(healthy, scale, good, bad, cfg):
    """Dynamic loss-scaling state transition (reference:
    operators/amp/update_loss_scaling_op.h Update<T>), fused into the
    step from the SAME health scalar the numeric fault guard computes —
    the scaler never re-reduces the grads:

      healthy: good+=1; bad=0; good==incr_every_n_steps -> scale*=incr
      tripped: bad+=1;  good=0; bad==decr_every_n_nan_or_inf -> scale*=decr
               (floored at 1.0 — the reference clamps the decayed scale
               so persistent overflow can't drive it to fp32 zero,
               where 0*incr == 0 sticks forever and the zeroed scaled
               loss would read as "healthy")

    All arrays are shape [1] (scale float, counters int32); ``healthy``
    is the scalar bool. Pure jnp, so the compiled path fuses it and the
    interpreter oracle runs the IDENTICAL arithmetic (bit-parity)."""
    good_i = good + 1
    bad_i = bad + 1
    incr_hit = good_i >= jnp.asarray(int(cfg["incr_every_n_steps"]),
                                     good.dtype)
    decr_hit = bad_i >= jnp.asarray(int(cfg["decr_every_n_nan_or_inf"]),
                                    bad.dtype)
    scale_good = jnp.where(incr_hit,
                           scale * jnp.asarray(cfg["incr_ratio"],
                                               scale.dtype), scale)
    scale_bad = jnp.where(decr_hit,
                          jnp.maximum(
                              scale * jnp.asarray(cfg["decr_ratio"],
                                                  scale.dtype),
                              jnp.asarray(1.0, scale.dtype)), scale)
    zero = jnp.zeros_like(good)
    new_scale = jnp.where(healthy, scale_good, scale_bad)
    new_good = jnp.where(healthy, jnp.where(incr_hit, zero, good_i), zero)
    new_bad = jnp.where(healthy, zero, jnp.where(decr_hit, zero, bad_i))
    return new_scale, new_good, new_bad


class _CompiledBlock:
    """One traced+jitted step function for (program, feeds, fetches)."""

    kind = "compiled"

    def __init__(self, program: Program, feed_names: Tuple[str, ...],
                 fetch_names: Tuple[str, ...], scope: Scope, seed: int,
                 mesh=None, param_shardings=None, feed_lods=None,
                 guard: bool = True):
        import weakref
        self._scope_ref = weakref.ref(scope)
        # trace-time-static LoD of feeds + initialized state vars
        self._init_lods: Dict[str, tuple] = dict(feed_lods or {})
        self.fetch_lods: List = [None] * len(fetch_names)
        self.mesh = mesh
        # name → PartitionSpec for tensor-parallel params (anything absent
        # is replicated); the optimizer state for a sharded param follows
        # the param's spec automatically when shapes match
        self.param_shardings = dict(param_shardings or {})
        self.program = program
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        block = program.global_block()
        ops = [op for op in block.ops if op.type not in ("feed", "fetch")]
        self.ops = ops

        # classify variables: read-before-write & initialized in scope -> state
        state_names, written = _classify_block_state(ops, block, feed_names,
                                                     scope)
        self.written = written
        # state vars that get overwritten -> donated & written back
        self.mut_state = tuple(n for n in state_names if n in written)
        self.ro_state = tuple(n for n in state_names if n not in written)
        for n in state_names:
            lv = _normalize_lod(scope.find_var(n).get_tensor().lod())
            if lv:
                self._init_lods.setdefault(n, lv)
        # persistable outputs not in state (e.g. newly created opt moments
        # already initialized by startup → they are in state; anything else
        # persistable written gets written back too)
        persistable = {v.name for v in block.vars.values() if v.persistable}
        self.extra_writeback = tuple(
            n for n in written
            if n in persistable and n not in self.mut_state
            and n not in feed_names)
        self.seed = seed
        self._init_guard(program, scope, enabled=guard)
        # PipelineOptimizer-sectioned program + a mesh with a "pp" axis:
        # lower the homogeneous interior onto the compiled gpipe schedule
        # (fused fallback with a warning otherwise)
        self._pipeline_plan = None
        popt = getattr(program, "_pipeline_opt", None)
        if popt and mesh is not None and "pp" in mesh.axis_names:
            from .pipeline_lowering import build_plan
            self._pipeline_plan = build_plan(self, popt)
        # RecomputeOptimizer checkpoints → jax.checkpoint segments
        self._remat_plan = None
        ropt = getattr(program, "_recompute_opt", None)
        if ropt and self._pipeline_plan is None:
            from .recompute_lowering import build_plan as build_remat
            self._remat_plan = build_remat(self, ropt["checkpoints"])
        elif ropt and self._pipeline_plan is not None:
            import warnings as _warnings
            _warnings.warn(
                "program carries BOTH pipeline sections and recompute "
                "checkpoints; the pipelined schedule runs and the "
                "checkpoints are NOT rematerialized", stacklevel=2)
        self._jitted = jax.jit(self._step, donate_argnums=(0,))
        # (n_steps, windowed-feed names) → scanned jit; shape changes
        # within a key retrace inside jax.jit as usual
        self._multi_jit: Dict[Tuple[int, Tuple[str, ...]], Any] = {}
        # step telemetry (docs/OBSERVABILITY.md): first dispatch of the
        # single-step jit bumps executor_compiles_total{kind="step"}
        self._dispatched = False

    # ---------------------------------------------- numeric fault guard
    def _init_guard(self, program: Program, scope: Scope,
                    enabled: bool = True):
        """Capture the numeric-fault-plane config at build time (the
        guard is BAKED into the trace; the Executor's program cache is
        keyed by the flags, so flipping them rebuilds rather than
        retraces per step — docs/FAULT_TOLERANCE.md "Numeric faults").

          _guard_check  FLAGS_check_nan_inf at build
          _guard_action raise | skip | rollback
          _amp          program._amp_dynamic (AMP dynamic loss scaling
                        state names + hyperparams) or None
          _guard_select True when the step must keep its pre-step state
                        reachable for the fused bad-step discard (skip/
                        rollback, and always under AMP — an overflowed
                        step is dropped, its scale update applied)

        Under select, initialized extra-writeback persistables are
        promoted into mut_state so the discard covers EVERY persistable
        the step writes, and the AMP state vars join mut_state so the
        epilogue's scale/counter updates thread through the step (and
        ride the lax.scan carry on the windowed path)."""
        if not enabled:
            # build-time opt-out (the dygraph tape op: no post-step
            # host hook exists there, so a baked-in guard would revert
            # NaN steps with nobody reading the verdict) — skipped
            # BEFORE any classification side effect (mut-state
            # promotion, AMP var splicing, scale-var init checks)
            self._guard_check = False
            self._guard_action = "raise"
            self._amp = None
            self._guard_select = False
            self._guard_active = False
            self._select_names = ()
            self._health_names: Tuple[str, ...] = ()
            return
        self._guard_check = bool(core.globals_["FLAGS_check_nan_inf"])
        self._guard_action = str(core.globals_["FLAGS_nan_inf_action"])
        if self._guard_check and self._guard_action not in _GUARD_ACTIONS:
            # a typo'd action must not silently disable every policy
            # while the check flag still claims protection is on
            raise ValueError(
                f"FLAGS_nan_inf_action={self._guard_action!r} is not one "
                f"of {sorted(_GUARD_ACTIONS)}")
        self._amp = getattr(program, "_amp_dynamic", None)
        if self._amp is not None and not _block_reads_amp_scale(
                self.ops, self._amp):
            # a clone/prune sliced the scaled-loss machinery away (e.g.
            # an eval program pruned to a forward fetch) — the epilogue
            # must NOT keep mutating the shared scale/counters there
            self._amp = None
        # raise keeps the select too: the localizer re-runs the tripped
        # step through the interpreter and needs exactly the pre-step
        # state to reproduce it
        self._guard_select = (self._amp is not None
                              or (self._guard_check and self._guard_action
                                  in ("raise", "skip", "rollback")))
        self._guard_active = self._guard_check or self._amp is not None
        if not self._guard_active:
            self._select_names: Tuple[str, ...] = ()
            return

        def _scope_tensor_ok(n):
            return _initialized_tensor(scope, n) is not None

        if self._guard_select:
            promoted = tuple(n for n in self.extra_writeback
                             if _scope_tensor_ok(n))
            if promoted:
                self.mut_state = self.mut_state + promoted
                self.extra_writeback = tuple(
                    n for n in self.extra_writeback if n not in promoted)
        if self._amp is not None:
            for n in (self._amp["scale"], self._amp["good"],
                      self._amp["bad"]):
                if n in self.ro_state:
                    self.ro_state = tuple(x for x in self.ro_state
                                          if x != n)
                if n not in self.mut_state:
                    if not _scope_tensor_ok(n):
                        raise RuntimeError(
                            f"AMP dynamic loss scaling var '{n}' is not "
                            f"initialized in the scope — run the startup "
                            f"program first")
                    self.mut_state = self.mut_state + (n,)
        # the bad-step discard covers exactly the state the step
        # overwrites; the AMP vars are epilogue-managed (never reverted
        # — a dropped step still updates the scale)
        amp_names = (set() if self._amp is None else
                     {self._amp["scale"], self._amp["good"],
                      self._amp["bad"]})
        self._select_names = tuple(
            n for n in self.mut_state
            if n in self.written and n not in amp_names)
        # health reduces over the PARAM GRADIENTS (+ float fetches), not
        # the updated params: finite grads into a finite optimizer step
        # keep params finite, and the health scalar is then available
        # BEFORE the update ops at the XLA level — no reduction barrier
        # on the new state (reducing the updated params measured 37%
        # lane overhead; the grad-sourced reduce itself measures ~0%,
        # every remaining cost is the discard select — BENCH_LOCAL
        # mnist_realdata_guard note). Param grads subsume activation
        # grads (chain rule drags any upstream NaN into them), and
        # skipping the batch-sized activation-grad reductions measured
        # ~9% of the lane back. Blocks with no param grads fall back to
        # all grads, then to the written state itself (inference/eval).
        grads = {n for n in self.written if n.endswith(GRAD_SUFFIX)}
        self._health_names = tuple(
            n + GRAD_SUFFIX for n in self._select_names
            if n + GRAD_SUFFIX in grads) or tuple(sorted(grads))

    def _warn_unselectable(self, name, old, new):
        """A state var whose SHAPE changed during the step cannot be
        selected back — on a tripped step it keeps its (possibly
        non-finite) post-step value while everything else reverts. That
        hole in the discard must be loud, once per var: a NaN parked
        there re-trips every following step and burns the rollback
        budget on what looked like a transient fault."""
        import warnings as _warnings
        warned = getattr(self, "_warned_unselectable", None)
        if warned is None:
            warned = self._warned_unselectable = set()
        if name in warned:
            return
        warned.add(name)
        _warnings.warn(
            f"numeric fault guard: state var '{name}' changes shape "
            f"during the step ({getattr(old, 'shape', None)} -> "
            f"{getattr(new, 'shape', None)}) and CANNOT be covered by "
            f"the bad-step discard — on a tripped step it keeps its "
            f"post-step value", stacklevel=3)

    def _guard_epilogue(self, orig_mut, new_mut, fetches, env):
        """Fused guard tail of one traced step: the single health
        scalar (over grads + float fetches — see _init_guard), the
        bad-step discard (select back to the pre-step state), and the
        AMP scale transition — all device-side, zero host round-trips.
        Returns (new_mut, health)."""
        from .ir import fused_health
        vals = [env[n] for n in self._health_names if n in env]
        if not vals:  # no grads in this block: reduce the state writes
            vals = [new_mut[n] for n in self._select_names
                    if n in new_mut]
        vals = vals + list(fetches)
        health = fused_health(vals)
        return self._apply_discard(new_mut, orig_mut, health), health

    def _apply_discard(self, store, orig, health):
        """The fused bad-step discard (select back to the pre-step
        state, shape-mismatch vars warned once) + the AMP scale
        transition, over one name→array mapping — ``new_mut`` for the
        fused epilogue, ``env`` for the segmented step. ONE
        implementation, so the paths whose bit-parity the design
        depends on cannot drift apart."""
        if self._guard_select:
            for n in self._select_names:
                new, old = store.get(n), orig.get(n)
                if new is None or old is None or new is old:
                    continue
                if getattr(new, "shape", None) == getattr(old, "shape",
                                                          None):
                    store[n] = jnp.where(health, new, old)
                else:
                    self._warn_unselectable(n, old, new)
        if self._amp is not None:
            a = self._amp
            olds = (store[a["scale"]], store[a["good"]], store[a["bad"]])
            news = _amp_scale_update(health, *olds, a)
            if self._guard_check and self._guard_action == "raise":
                # raise mode replays the tripped step through the
                # interpreter localizer from its exact pre-step state —
                # INCLUDING the loss scale: letting the decay land first
                # would shrink loss*scale on the replay, the overflow
                # would not reproduce, and the localizer would mis-report
                # "the fault did not replay". The scale vars are
                # epilogue-managed (step ops only read them), so the
                # pre-transition values ARE the pre-step values.
                news = tuple(jnp.where(health, nv, ov)
                             for nv, ov in zip(news, olds))
            store[a["scale"]], store[a["good"]], store[a["bad"]] = news
        return store

    def _step(self, mut_state: Dict[str, Any], ro_state: Dict[str, Any],
              feeds: Dict[str, Any], rng):
        # the pre-step state refs stay reachable for the guard's fused
        # bad-step discard (jax arrays are immutable; XLA resolves the
        # donation aliasing)
        orig_mut = dict(mut_state) if self._guard_select else None
        env: Dict[str, Any] = {}
        env.update(ro_state)
        env.update(mut_state)
        env.update(feeds)
        lod_env: Dict[str, tuple] = dict(self._init_lods)
        if self._pipeline_plan is not None:
            from .pipeline_lowering import exec_plan
            exec_plan(self, self._pipeline_plan, env, lod_env, rng)
        elif self._remat_plan is not None:
            from .recompute_lowering import exec_plan as exec_remat
            exec_remat(self, self._remat_plan, env, lod_env, rng)
        else:
            self._exec_ops(self.ops, env, lod_env, rng)
        fetches = []
        for i, n in enumerate(self.fetch_names):
            if n not in env:
                raise KeyError(f"fetch var '{n}' not produced by program")
            fetches.append(env[n])
            self.fetch_lods[i] = lod_env.get(n)
        new_mut = {n: env[n] for n in self.mut_state}
        extra = {n: env[n] for n in self.extra_writeback if n in env}
        health = jnp.bool_(True)
        if self._guard_active:
            new_mut, health = self._guard_epilogue(orig_mut, new_mut,
                                                   fetches, env)
        return fetches, new_mut, extra, health

    # -------------------------------------------------- control-flow lowering
    # The reference interprets while/conditional_block by re-entering the
    # scope-based executor on the sub-block (while_op.cc,
    # conditional_block_op.cc). Compiled lowering instead: conditional
    # branches trace unconditionally and merge at select_input (on TPU a
    # vectorized select is the idiomatic lowering — lax.cond frequently
    # becomes a select anyway), and `while` becomes lax.while_loop with the
    # loop-carried names as the carry dict.
    def _exec_while(self, op, env, lod_env, rng):
        import jax.lax as lax
        sub = op.attrs["sub_block"]
        cond_name = op.inputs["Condition"][0]
        x_names = list(op.inputs.get("X", []))
        written = set()
        for sop in sub.ops:
            written.update(sop.output_arg_names)
        out_names = [n for n in op.outputs.get("Out", []) if n in env]
        carry_names = sorted({cond_name}
                             | set(out_names)
                             | {n for n in x_names
                                if n in written and n in env})
        missing = [n for n in carry_names if n not in env]
        if missing:
            raise KeyError(
                f"while op reads undefined vars {missing} — outer program "
                f"did not produce them")
        base_env = dict(env)
        sub_ops = sub.ops
        _IT = "@while_iter@"  # loop counter so per-iteration RNG differs

        def cond_fn(carry):
            return jnp.reshape(carry[cond_name], ()).astype(bool)

        def body_fn(carry):
            e = dict(base_env)
            it = carry[_IT]
            e.update({n: v for n, v in carry.items() if n != _IT})
            le = dict(lod_env)
            self._exec_ops(sub_ops, e, le, jax.random.fold_in(rng, it))
            out = {n: e[n] for n in carry_names}
            out[_IT] = it + 1
            return out

        init = {n: env[n] for n in carry_names}
        init[_IT] = jnp.zeros((), jnp.int32)
        final = lax.while_loop(cond_fn, body_fn, init)
        final.pop(_IT, None)
        env.update(final)

    def _exec_ops(self, ops, env, lod_env, rng, idx0=0):
        # ``idx0``: global index of ops[0] in the block's (feed/fetch-free)
        # op list — per-op rng keys fold from GLOBAL indices so a segmented
        # run draws the same streams as the fused compiled run would
        for local_idx, op in enumerate(ops):
            idx = idx0 + local_idx
            otype = op.type
            if otype == "while":
                self._exec_while(op, env, lod_env, rng)
                continue
            if otype in ("conditional_block", "conditional_block_infer"):
                # Trace the branch unconditionally on an env COPY (both-
                # branch compute = TPU select idiom), then mask-merge any
                # write to a pre-existing outer var so the untaken branch
                # cannot clobber state; fresh vars flow through for
                # select_input to pick.
                branch_env = dict(env)
                self._exec_ops(op.attrs["sub_block"].ops, branch_env,
                               lod_env, rng)
                cnames = op.inputs.get("Cond") or []
                mask = (jnp.reshape(env[cnames[0]], ()) != 0) \
                    if cnames and cnames[0] in env else None
                for n, v in branch_env.items():
                    old = env.get(n)
                    if old is v:
                        continue
                    if old is None or mask is None:
                        env[n] = v
                    elif getattr(old, "shape", None) == getattr(v, "shape",
                                                                None):
                        env[n] = jnp.where(mask, v, old)
                    else:
                        raise NotImplementedError(
                            f"conditional_block branch changes the shape of "
                            f"outer var '{n}' ({getattr(old, 'shape', None)}"
                            f" -> {getattr(v, 'shape', None)}); conditional "
                            f"shape-changing writes cannot be compiled — "
                            f"produce a new variable instead")
                continue
            if otype == "select_input":
                mask = jnp.reshape(env[op.inputs["Mask"][0]], ()) != 0
                xf = env.get(op.inputs["X"][0])
                xt = env.get(op.inputs["X"][1])
                if xf is None or xt is None:
                    picked = xt if xf is None else xf
                elif xt.shape == xf.shape:
                    picked = jnp.where(mask, xt, xf)
                else:
                    raise NotImplementedError(
                        f"cond branches produce different shapes "
                        f"({xt.shape} vs {xf.shape}) for the same output — "
                        f"XLA needs matching branch shapes; pad or "
                        f"restructure the branches")
                env[op.outputs["Out"][0]] = picked
                continue
            ins = {}
            for slot, names in op.inputs.items():
                ins[slot] = [env.get(n) for n in names]
            attrs = op.attrs
            in_lods = _collect_in_lods(op, lod_env.get)
            if _op_needs_lod(op):
                attrs = dict(attrs)
                attrs["_lod"] = in_lods
            if OPS.has(otype):
                info = OPS.get(otype)
                if info.needs_rng:
                    attrs = dict(attrs)
                    if attrs.get("fix_seed", False) or attrs.get("seed", 0):
                        attrs["_rng"] = jax.random.key(int(attrs.get("seed", 0)))
                    else:
                        attrs["_rng"] = jax.random.fold_in(rng, idx)
                outs = info.kernel(ins, attrs)
            elif otype.endswith("_grad") and OPS.has(otype[:-5]):
                base = OPS.get(otype[:-5])
                if base.needs_rng:
                    # same key as the forward op (stamped _fwd_idx) so the
                    # vjp re-run samples identically
                    attrs = dict(attrs)
                    attrs["_rng"] = jax.random.fold_in(
                        rng, int(attrs.get("_fwd_idx", idx)))
                outs = run_generic_grad(
                    otype[:-5], ins, attrs,
                    wanted_grad_slots=list(op.outputs.keys()),
                    fwd_input_slots=attrs.get("_fwd_in", list(op.inputs.keys())))
            elif otype.endswith("_grad_grad") and OPS.has(otype[:-10]):
                # static double grad: vjp THROUGH the generic grad of the
                # base op (gradient-penalty losses differentiate *_grad
                # ops; reference imperative/partial_grad_engine.cc role)
                from ..ops.registry import run_generic_grad_grad
                if OPS.get(otype[:-10]).needs_rng:
                    # same key as the forward op, like the *_grad branch:
                    # the doubly-nested vjp must replay the SAME draws
                    attrs = dict(attrs)
                    attrs["_rng"] = jax.random.fold_in(
                        rng, int(attrs.get("_fwd_idx", idx)))
                outs = run_generic_grad_grad(
                    otype[:-10], ins, attrs,
                    wanted_grad_slots=list(op.outputs.keys()),
                    gradop_slots=attrs.get("_fwd_in",
                                           list(op.inputs.keys())))
            else:
                raise NotImplementedError(f"op {otype} not registered")
            for slot, names in op.outputs.items():
                vals = outs.get(slot)
                if vals is None:
                    continue
                for n, v in zip(names, vals):
                    if v is not None and n != "@EMPTY@":
                        env[n] = v
            _propagate_lods(
                op, outs, in_lods,
                lod_env.__setitem__,
                lambda n: (env[n].shape[0] if n in env and
                           getattr(env[n], "ndim", 0) else None))

    def _place_inputs(self, scope: Scope, feeds: Dict[str, Any], rng,
                      window_names=()):
        """State from the scope + feeds, device-placed for the step (mesh
        sharding applied when data-parallel). Shared by run() and by
        HLO-inspection helpers (lowered()). Feeds named in
        ``window_names`` are [K, batch, ...] window STACKS: their batch
        dim is dim 1, so the mesh placement shards THAT dim over "dp"
        and leaves the window dim whole for the scan (one device_put
        per window — docs/INPUT_PIPELINE.md)."""
        mut = {n: scope.find_var(n).get_tensor().array for n in self.mut_state}
        ro = {n: scope.find_var(n).get_tensor().array for n in self.ro_state}
        if self.mesh is not None:
            # data-parallel placement: params/state replicated, feed batch
            # sharded on the dp axis. XLA's sharding propagation inserts the
            # grad all-reduces over ICI (replaces reference allreduce
            # op-handles — multi_devices_graph_pass.cc:604).
            from ..parallel.mesh import replicated, shard_feed
            from jax.sharding import NamedSharding
            repl = replicated(self.mesh)

            multiproc = jax.process_count() > 1

            def place(n, a):
                spec = self._sharding_for(n, a)
                sh = repl if spec is None else NamedSharding(self.mesh, spec)
                if multiproc:
                    if isinstance(a, jax.Array) and not a.is_fully_addressable:
                        return a  # already global (written back last step)
                    # device_put can't target non-addressable devices; every
                    # process holds the full value (startup ran identically
                    # on all ranks), so assemble the global array from the
                    # process-local copy. global_shape MUST be passed: it is
                    # the documented "data is identical across hosts" mode —
                    # without it a cross-process sharded dim would be
                    # inferred as local_size × process_slices (2× too big)
                    host = np.asarray(a)
                    return jax.make_array_from_process_local_data(
                        sh, host, global_shape=host.shape)
                return jax.device_put(a, sh)
            mut = {n: place(n, a) for n, a in mut.items()}
            ro = {n: place(n, a) for n, a in ro.items()}
            feeds = {n: shard_feed(self.mesh, n, a,
                                   window=n in window_names)
                     for n, a in feeds.items()}
            if not multiproc:
                # multi-process: leave the key uncommitted — identical on
                # every rank, jit replicates it (key arrays can't go
                # through make_array_from_process_local_data)
                rng = jax.device_put(rng, repl)
        return mut, ro, feeds, rng

    def lowered(self, scope: Scope, feeds: Dict[str, Any], rng):
        """jax lowering of the single-step function over the CURRENT scope
        state — ``.compile().as_text()`` is the optimized HLO the step
        actually runs (donated aliases, collectives, fusions). Used by
        tests/test_ir_passes.py to EVIDENCE the absorbed-pass claims."""
        mut, ro, feeds, rng = self._place_inputs(scope, feeds, rng)
        return self._jitted.lower(mut, ro, feeds, rng)

    def run(self, scope: Scope, feeds: Dict[str, Any], rng):
        """One training/inference step: ONE dispatch of the jitted step.
        Returns (fetches, health) — health is the step's fused finite
        scalar (constant True when the guard is off), LAZY on device so
        the happy path costs no host sync."""
        mut, ro, feeds, rng = self._place_inputs(scope, feeds, rng)
        from . import profiler as _profiler
        first = not self._dispatched
        if first:
            self._dispatched = True
            _telemetry.count_compile("step")
        if _profiler.is_profiling():
            # the whole program is ONE dispatch on TPU — a single span
            # (per-op timing lives in the device XPlane trace). The
            # first dispatch additionally carries a cat="compile" span:
            # that is where jax traces+compiles the step (the backend
            # listener records the exact compile durations inside it).
            with _profiler.RecordEvent("compiled_step"):
                cm = (_profiler.RecordEvent("compile:step",
                                            cat="compile")
                      if first else contextlib.nullcontext())
                with cm:
                    fetches, new_mut, extra, health = self._jitted(
                        mut, ro, feeds, rng)
                    if _profiler.is_session():
                        # only a real profiler session pays the sync;
                        # shard-only spans measure dispatch
                        jax.block_until_ready(fetches)
        else:
            fetches, new_mut, extra, health = self._jitted(mut, ro, feeds,
                                                           rng)
        self._write_back(scope, new_mut, extra)
        return fetches, health

    def run_window(self, scope: Scope, feeds: Dict[str, Any], rng_base,
                   idx0: int, n_steps: int, window_names=()):
        """``n_steps`` as ONE dispatched lax.scan window. Feeds named in
        ``window_names`` carry a leading [n_steps, ...] dim of *distinct*
        batches consumed one slice per step (scan xs); every other feed
        broadcasts to all steps (the degenerate same-feeds mode — the
        pre-window benchmark shape). Host and wire costs (TPU-tunnel RTT
        ≈ 10 ms/dispatch) amortize to one dispatch per window. Fetches
        come back stacked [n_steps, ...], and so does the per-step
        health flag ([n_steps] bool; the guard rides the scan carry —
        a bad step's discard selects against THAT step's carry-in, so
        step i+1 of a faulted window continues from step i's pre-fault
        state)."""
        mut, ro, feeds, rng_base = self._place_inputs(
            scope, feeds, rng_base, window_names=window_names)
        from . import profiler as _profiler
        if _profiler.is_profiling():
            tag = "realdata" if window_names else "broadcast"
            with _profiler.RecordEvent(f"window[{n_steps}]:{tag}",
                                       cat="window"):
                fetches, new_mut, extra, health = self._run_multi(
                    mut, ro, feeds, rng_base, idx0, n_steps, window_names)
                if _profiler.is_session():
                    jax.block_until_ready(fetches)
        else:
            fetches, new_mut, extra, health = self._run_multi(
                mut, ro, feeds, rng_base, idx0, n_steps, window_names)
        self._write_back(scope, new_mut, extra)
        return fetches, health

    def _write_back(self, scope, new_mut, extra):
        for n, v in {**new_mut, **extra}.items():
            scope.var(n).set_value(LoDTensor(v))

    def _run_multi(self, mut, ro, feeds, rng_base, idx0, n_steps,
                   window_names):
        """The scanned window body. ``rng_base`` is the UNfolded program
        key and ``idx0`` the global step index of the window's first
        step: per-step keys fold by global index (idx0 + i), which are
        EXACTLY the keys ``n_steps`` sequential single-step run() calls
        would draw — windowed and per-step training see identical rng
        streams. Programs with extra-writeback vars fall back to a
        per-step dispatch loop with the same stacked-fetch contract.
        LoD-carrying fetches are refused: a single-step LoD cannot
        describe a stacked [n_steps, ...] dim."""
        self._check_no_lod_fetch()
        xs = {n: feeds[n] for n in window_names}
        bcast = {n: v for n, v in feeds.items() if n not in window_names}
        if not self.extra_writeback:
            key = (n_steps, tuple(sorted(window_names)))
            jitted = self._multi_jit.get(key)
            fresh = jitted is None
            if fresh:
                # a miss AFTER warm-up is a retrace (a new window/bucket
                # signature appeared late) — the scrapeable form of the
                # serving plane's no-recompile claim
                _telemetry.count_compile(
                    "window", retrace=bool(self._multi_jit))
                from jax import lax

                def many(mut, ro, bcast, xs, rng_b, i0):
                    def body(mut_c, x):
                        i, sl = x
                        f = dict(bcast)
                        f.update(sl)
                        fetches, new_mut, _, health = self._step(
                            mut_c, ro, f, jax.random.fold_in(rng_b, i))
                        return new_mut, (fetches, health)
                    new_mut, (ys, healths) = lax.scan(
                        body, mut, (i0 + jnp.arange(n_steps), xs))
                    return ys, new_mut, healths
                jitted = jax.jit(many, donate_argnums=(0,))
                self._multi_jit[key] = jitted
            from . import profiler as _profiler
            if fresh and _profiler.is_profiling():
                with _profiler.RecordEvent(
                        f"compile:window[{n_steps}]", cat="compile",
                        args={"n_steps": int(n_steps)}):
                    ys, new_mut, healths = jitted(mut, ro, bcast, xs,
                                                  rng_base,
                                                  jnp.int32(idx0))
            else:
                ys, new_mut, healths = jitted(mut, ro, bcast, xs,
                                              rng_base, jnp.int32(idx0))
            self._check_no_lod_fetch()  # lods appear during the trace
            return ys, new_mut, {}, healths
        per_step = []
        step_health = []
        extra = {}
        for i in range(n_steps):
            f = dict(bcast)
            for n, a in xs.items():
                f[n] = a[i]
            fetches, mut, extra, health = self._jitted(
                mut, ro, f, jax.random.fold_in(rng_base, idx0 + i))
            per_step.append(fetches)
            step_health.append(health)
        self._check_no_lod_fetch()
        stacked = [jnp.stack([s[k] for s in per_step])
                   for k in range(len(self.fetch_names))]
        return stacked, mut, extra, jnp.stack(step_health)

    def _check_no_lod_fetch(self):
        if any(l is not None for l in self.fetch_lods):
            raise NotImplementedError(
                "n_steps > 1 cannot stack LoD-carrying fetches — fetch "
                "dense vars or run per-step (n_steps=1)")

    def _sharding_for(self, name: str, a):
        """TP spec for a state var: exact param match, or an optimizer
        accumulator named '<param>_<acc>' with the param's shape."""
        spec = self.param_shardings.get(name)
        if spec is not None:
            return spec
        for pname, pspec in self.param_shardings.items():
            if name.startswith(pname + "_"):
                try:
                    ndim = len(pspec)
                except TypeError:
                    return None
                if hasattr(a, "ndim") and a.ndim == ndim:
                    return pspec
        return None


class _NotSegmentable(Exception):
    """Raised at build time when a block gains nothing from segmentation
    (no/too-few compilable ops) — the caller falls back to the pure
    interpreter quietly."""


def _effective_reads(op) -> List[str]:
    """Names an op may read, including through its sub-blocks (an island
    while/conditional re-enters the eager executor on the sub-block, whose
    ops read the scope directly)."""
    names = list(op.input_arg_names)
    stack = [op.attrs.get("sub_block")]
    while stack:
        b = stack.pop()
        if b is None:
            continue
        for sop in b.ops:
            names.extend(sop.input_arg_names)
            stack.append(sop.attrs.get("sub_block"))
    return names


def _effective_writes(op) -> List[str]:
    names = list(op.output_arg_names)
    stack = [op.attrs.get("sub_block")]
    while stack:
        b = stack.pop()
        if b is None:
            continue
        for sop in b.ops:
            names.extend(sop.output_arg_names)
            stack.append(sop.attrs.get("sub_block"))
    return names


class _SegmentedBlock(_CompiledBlock):
    """Segmented compilation: the block's op list partitioned into maximal
    pure runs — each traced+jitted as its own donated step — separated by
    stateful/host-op *islands* the interpreter dispatches eagerly.

    Kills the whole-block interpreter cliff: before this, ONE stateful op
    (auc, print, read, ...) among hundreds routed the ENTIRE block to
    op-by-op interpretation with per-op host sync (`_ops_compilable` at
    the top of Executor.run is all-or-nothing). The reference pays per-op
    dispatch everywhere by design (executor.cc:469-475); this build pays
    it only at the islands — fwd+bwd+optimizer stay fused XLA
    computations.

    Env handoff contract: one step threads a host-side ``env`` dict of
    DEVICE arrays through the segments in program order. Compiled segments
    consume/produce env entries through their jitted functions (state they
    overwrite is donated, exactly like the fused path); islands read env
    values pushed into the scope (a LoDTensor wrap of the device array —
    no host copy; only values the island actually reads are pushed) and
    their scope writes are pulled back into env. Values cross segment
    boundaries on device — the only host syncs are the ones island kernels
    themselves perform (e.g. auc's histogram update).

    Inherits the op tracing/lowering machinery from _CompiledBlock; the
    whole-step jit, pipeline/remat plans and multi-step scan are replaced
    by the per-segment plan (islands have per-step side effects, so
    multi-step windows run as a host loop in Executor.run)."""

    kind = "segmented"

    def __init__(self, program: Program, feed_names: Tuple[str, ...],
                 fetch_names: Tuple[str, ...], scope: Scope, seed: int,
                 feed_lods=None, seg_min_ops: Optional[int] = None):
        from .ir import analyze_block_segments
        self._scope_ref = weakref.ref(scope)
        self._init_lods: Dict[str, tuple] = dict(feed_lods or {})
        self.fetch_lods: List = [None] * len(fetch_names)
        self.mesh = None
        self.param_shardings = {}
        self.program = program
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        block = program.global_block()
        ops = [op for op in block.ops if op.type not in ("feed", "fetch")]
        self.ops = ops
        self.seed = seed
        self._pipeline_plan = None
        self._remat_plan = None

        self.segments = analyze_block_segments(ops)
        n_compilable = sum(len(s.ops) for s in self.segments
                           if s.kind == "compiled")
        if seg_min_ops is None:
            seg_min_ops = core.globals_["FLAGS_executor_seg_min_ops"]
        if n_compilable < seg_min_ops:
            raise _NotSegmentable(
                f"only {n_compilable} compilable ops (< "
                f"FLAGS_executor_seg_min_ops)")

        state_names, written = _classify_block_state(ops, block, feed_names,
                                                     scope)
        self.written = written
        self.mut_state = tuple(n for n in state_names if n in written)
        self.ro_state = tuple(n for n in state_names if n not in written)
        for n in state_names:
            lv = _normalize_lod(scope.find_var(n).get_tensor().lod())
            if lv:
                self._init_lods.setdefault(n, lv)
        persistable = {v.name for v in block.vars.values() if v.persistable}
        self.extra_writeback = tuple(
            n for n in written
            if n in persistable and n not in self.mut_state
            and n not in feed_names)
        self._init_guard(program, scope)

        # ---- per-segment dataflow: external reads / writes -------------
        seg_reads: List[List[str]] = []
        seg_writes: List[set] = []
        for seg in self.segments:
            reads: List[str] = []
            written_in: set = set()
            op_io = []
            for op in seg.ops:
                r, w = _effective_reads(op), _effective_writes(op)
                op_io.append((op, r, w))
                for n in r:
                    if n not in written_in and n not in reads:
                        reads.append(n)
                written_in.update(w)
            seg_reads.append(reads)
            seg_writes.append(written_in)
            if seg.kind == "island":
                # static per-op read/write lists: the island dispatch
                # pushes/pulls these every step — don't re-walk sub-block
                # trees on the hot path
                seg.op_io = op_io

        # fetch names must be resolvable BEFORE anything runs: a compiled
        # segment may donate state buffers, so failing at fetch-collection
        # time (the interpreter's behavior) would leave the scope pointing
        # at deleted arrays
        producible = set()
        for w in seg_writes:
            producible |= w
        for n in fetch_names:
            if n not in producible and n not in state_names \
                    and n not in feed_names and scope.find_var(n) is None:
                raise KeyError(f"fetch var '{n}' not produced by program")

        # liveness: a compiled segment only returns what someone later
        # needs (later segments/islands, the fetch list, state/persistable
        # writeback); state it overwrites is donated — whole-state
        # donation, segment by segment
        need_at_end = (set(fetch_names) | set(self.mut_state)
                       | set(self.extra_writeback))
        donatable = set(self.mut_state)
        for i, seg in enumerate(self.segments):
            if seg.kind != "compiled":
                continue
            later_reads: set = set()
            for r in seg_reads[i + 1:]:
                later_reads.update(r)
            seg.out_names = tuple(sorted(
                n for n in seg_writes[i]
                if n in later_reads or n in need_at_end))
            seg.donated_names = tuple(sorted(
                n for n in seg_reads[i]
                if n in donatable and n in seg_writes[i]))
            seg.in_names = tuple(sorted(
                set(seg_reads[i]) - set(seg.donated_names)))
            if self._guard_select:
                # the fused bad-step discard needs the step's pre-state
                # refs alive until the select at the end of run_step —
                # per-segment donation would delete them mid-step
                seg.in_names = tuple(sorted(
                    set(seg.in_names) | set(seg.donated_names)))
                seg.donated_names = ()
            seg.guard_names = ()
            seg._cache = {}  # lod-key -> [jitted step, captured out lods]

    # -------------------------------------------------------------- step
    def _seg_dispatch(self, seg, env, lod_env, rng, profiling):
        """Run one compiled segment: jit-cache keyed by the LoD of its
        inputs (trace-time-static, same contract as the fused path's
        feed-LoD-keyed program cache). When the numeric fault guard is
        on, a per-segment finite check over the segment's float outputs
        is FUSED into the jitted step and returned as one extra bool —
        run_step ANDs the flags into the step health with no host sync.
        Returns (outs, health_flag_or_None)."""
        from .ir import fused_health, guarded_float_names
        in_all = seg.in_names + seg.donated_names
        lkey = tuple((n, lod_env[n]) for n in in_all if n in lod_env)
        entry = seg._cache.get(lkey)
        first = entry is None
        if first:
            # a new LoD key on a warm segment cache IS a retrace
            _telemetry.count_compile("segment",
                                     retrace=bool(seg._cache))
            static_lods = dict(lkey)
            captured: Dict[str, Any] = {}
            seg_ops, start, out_names = seg.ops, seg.start, seg.out_names
            guard = self._guard_active

            def step(donated, held, rng_):
                e = dict(held)
                e.update(donated)
                le = dict(static_lods)
                self._exec_ops(seg_ops, e, le, rng_, idx0=start)
                captured.clear()
                captured.update({n: le[n] for n in out_names if n in le})
                res = {n: e[n] for n in out_names if n in e}
                if not guard:
                    return res, jnp.bool_(True)
                seg.guard_names = tuple(guarded_float_names(out_names, e))
                return res, fused_health(
                    [e[n] for n in seg.guard_names])

            entry = seg._cache[lkey] = [
                jax.jit(step, donate_argnums=(0,)), captured]
        jitted, captured = entry
        donated = {n: env[n] for n in seg.donated_names if n in env}
        held = {n: env[n] for n in seg.in_names if n in env}
        if profiling:
            from . import profiler as _profiler
            tag = "compile" if first else "exec"
            with _profiler.RecordEvent(
                    f"segment[{seg.start}:{seg.stop}]:{tag}",
                    cat="segment"):
                outs, seg_health = jitted(donated, held, rng)
                if _profiler.is_session():
                    jax.block_until_ready(outs)
        else:
            outs, seg_health = jitted(donated, held, rng)
        env.update(outs)
        for n, lv in captured.items():
            if lv:
                lod_env[n] = lv
        return outs, (seg_health if self._guard_active else None)

    def _island_dispatch(self, seg, env, lod_env, rng, scope, executor,
                         profiling):
        """Run one island through the eager interpreter: push the env
        values the island reads into the scope (device-array wrap, no host
        copy), dispatch each op, pull its writes back into env."""
        ctx = None
        if profiling:
            from . import profiler as _profiler
            ctx = _profiler.RecordEvent(
                f"island[{seg.start}:{seg.stop}]:"
                + ",".join(sorted({o.type for o in seg.ops})),
                cat="segment")
            ctx.__enter__()
        try:
            for off, (op, op_reads, op_writes) in enumerate(seg.op_io):
                for n in op_reads:
                    if n in env:
                        scope.var(n).set_value(
                            LoDTensor(env[n], lod_env.get(n)))
                executor._run_op_eager(op, scope, rng, seg.start + off)
                for n in op_writes:
                    v = scope.find_var(n)
                    if v is None or not v.is_initialized():
                        continue
                    val = v.value()
                    if isinstance(val, LoDTensor) and val.array is not None:
                        env[n] = val.array
                        lv = _normalize_lod(val.lod())
                        if lv:
                            lod_env[n] = lv
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)

    def run_step(self, scope: Scope, feeds: Dict[str, Any], rng, executor):
        """One training/inference step through the segment plan. Returns
        (fetch arrays, fetch lods, health). Health is the AND of every
        compiled segment's fused finite flag, the islands' written float
        env values, and the float fetches — all device-side, so the
        happy path stays sync-free. Under a select action (skip/
        rollback/AMP) a tripped step's state writes select back to
        their pre-step values; island-INTERNAL side effects (an auc
        histogram, a print) cannot be unwound and are documented as
        out of the discard's reach."""
        from . import profiler as _profiler
        from .ir import fused_health
        profiling = _profiler.is_profiling()
        env: Dict[str, Any] = {}
        for n in self.ro_state + self.mut_state:
            env[n] = scope.find_var(n).get_tensor().array
        env.update(feeds)
        orig = ({n: env[n] for n in self._select_names if n in env}
                if self._guard_select else None)
        lod_env: Dict[str, tuple] = dict(self._init_lods)
        n_comp = sum(1 for s in self.segments if s.kind == "compiled")
        seg_flags: List[Tuple[str, Any]] = []  # (segment label, bool flag)
        try:
            with _profiler.RecordEvent(
                    f"segmented_step[{n_comp}c/"
                    f"{len(self.segments) - n_comp}i]", cat="segment") \
                    if profiling else contextlib.nullcontext():
                for seg in self.segments:
                    if seg.kind == "compiled":
                        _outs, flag = self._seg_dispatch(
                            seg, env, lod_env, rng, profiling)
                        if flag is not None:
                            seg_flags.append(
                                (f"segment[{seg.start}:{seg.stop}]", flag))
                    else:
                        self._island_dispatch(seg, env, lod_env, rng,
                                              scope, executor, profiling)
                        if self._guard_active:
                            written = {n for _op, _r, w in seg.op_io
                                       for n in w}
                            vals = [env[n] for n in sorted(written)
                                    if n in env]
                            seg_flags.append(
                                (f"island[{seg.start}:{seg.stop}]",
                                 fused_health(vals)))
        except Exception:
            if orig is not None:
                # guard-select runs promise the PRE-step state on any
                # trip — an island's raise-mode localizer fires mid-step
                # (before the end-of-step select), so earlier segments'
                # partial writes must not be committed (donation is
                # disabled under select, the refs are intact)
                env.update(orig)
            # a failure AFTER a donating segment ran would leave the scope
            # pointing at deleted buffers; restore the freshest state
            # (interpreter-like partial-step semantics for unguarded
            # runs) before surfacing
            self._write_back_state(scope, env, lod_env)
            raise
        fetched, fetch_lods = [], []
        for n in self.fetch_names:
            if n in env:
                fetched.append(env[n])
                fetch_lods.append(lod_env.get(n))
                continue
            v = scope.find_var(n)
            if v is None or not v.is_initialized():
                raise KeyError(f"fetch var '{n}' not produced by program")
            val = v.value()
            if isinstance(val, LoDTensor):
                fetched.append(val.array)
                fetch_lods.append(_normalize_lod(val.lod()))
            else:
                fetched.append(val)
                fetch_lods.append(None)
        self.fetch_lods = fetch_lods
        health = jnp.bool_(True)
        if self._guard_active:
            health = fused_health(list(fetched))
            for _label, flag in seg_flags:
                health = jnp.logical_and(health, flag)
            self._last_seg_flags = seg_flags  # trip localization (lazy)
            self._apply_discard(env, orig, health)
        self._write_back_state(scope, env, lod_env)
        return fetched, fetch_lods, health

    def _write_back_state(self, scope, env, lod_env):
        for n in self.mut_state + self.extra_writeback:
            v = env.get(n)
            if v is None:
                continue
            if isinstance(v, jax.Array) and v.is_deleted():
                continue  # donated by a segment that then failed mid-run
            scope.var(n).set_value(LoDTensor(v, lod_env.get(n)))


class HealthMonitor:
    """Rollback policy engine of the numeric fault plane
    (FLAGS_nan_inf_action=rollback — docs/FAULT_TOLERANCE.md "Numeric
    faults"). Consumes the per-step fused health flag the compiled/
    windowed/segmented paths already produce; after
    ``tolerance`` CONSECUTIVE tripped steps it restores the last intact
    PR-3 checkpoint under ``ckpt_dir`` (parameters, optimizer slots,
    rng fold counter, optional DataLoader position — bit-exact, so the
    re-run of the faulted window matches an oracle that never saw the
    fault). At most ``max_rollbacks`` restores; the next trip past that
    (or a trip with no intact checkpoint to restore) raises
    ``core.NumericFaultError``. Until tolerance is reached, tripped
    steps are discarded by the fused skip-select, so state never holds
    a NaN between observations."""

    def __init__(self, executor, ckpt_dir, program=None, scope=None,
                 tolerance: Optional[int] = None,
                 max_rollbacks: Optional[int] = None, dataloader=None,
                 on_rollback=None):
        self.executor = executor
        self.ckpt_dir = ckpt_dir
        self.program = program
        self.scope = scope
        self.dataloader = dataloader
        self.on_rollback = on_rollback
        self.tolerance = max(1, int(
            core.globals_["FLAGS_nan_inf_tolerance"]
            if tolerance is None else tolerance))
        self.max_rollbacks = int(
            core.globals_["FLAGS_nan_inf_max_rollbacks"]
            if max_rollbacks is None else max_rollbacks)
        self.trips = 0
        self.consecutive_bad = 0
        self.rollbacks = 0
        self.last_trip_step: Optional[int] = None
        self.last_rollback_step: Optional[int] = None
        self.last_manifest: Optional[Dict[str, Any]] = None

    def observe(self, healthy: bool, step: int) -> str:
        """Feed one step's health verdict. Returns "ok" | "tripped" |
        "rolled_back"; raises core.NumericFaultError when the retry
        budget is spent."""
        if healthy:
            self.consecutive_bad = 0
            return "ok"
        from . import profiler as _profiler
        self.trips += 1
        self.consecutive_bad += 1
        self.last_trip_step = int(step)
        _profiler.record_instant(
            f"health:trip[step {step}]", cat="health",
            args={"step": int(step), "action": "rollback",
                  "consecutive_bad": self.consecutive_bad})
        if self.consecutive_bad < self.tolerance:
            return "tripped"
        return self._rollback(step)

    def _rollback(self, step: int) -> str:
        from . import io as _io
        from . import profiler as _profiler
        if self.rollbacks >= self.max_rollbacks:
            raise core.NumericFaultError(
                f"numeric fault at step {step}: "
                f"{self.consecutive_bad} consecutive non-finite steps "
                f"and the rollback budget "
                f"(FLAGS_nan_inf_max_rollbacks={self.max_rollbacks}) is "
                f"spent — the fault is persistent, not transient")
        scope = self.scope if self.scope is not None else global_scope()
        manifest = _io.rollback_to_latest(self.executor, self.ckpt_dir,
                                          main_program=self.program,
                                          scope=scope)
        if manifest is None:
            raise core.NumericFaultError(
                f"numeric fault at step {step}: "
                f"FLAGS_nan_inf_action=rollback but no intact checkpoint "
                f"under {self.ckpt_dir!r} to roll back to")
        if self.dataloader is not None and manifest.get("dataloader"):
            self.dataloader.load_state_dict(manifest["dataloader"])
        self.rollbacks += 1
        self.consecutive_bad = 0
        self.last_rollback_step = int(step)
        self.last_manifest = manifest
        cfg = self.executor._auto_ckpt
        if cfg is not None:
            cfg["last_step"] = int(manifest["global_step"])
        _profiler.record_instant(
            f"health:rollback[step {step}->"
            f"{manifest['global_step']}]", cat="health",
            args={"step": int(step), "action": "rollback",
                  "restored_step": int(manifest["global_step"]),
                  "rollbacks": self.rollbacks})
        if self.on_rollback is not None:
            self.on_rollback(manifest)
        return "rolled_back"


class Executor:
    """Drop-in equivalent of fluid.Executor (reference executor.py:457)."""

    def __init__(self, place=None):
        self.place = place if place is not None else (
            core.TPUPlace(0) if core.is_compiled_with_tpu() else core.CPUPlace())
        self._compiled_cache: Dict[Tuple, _CompiledBlock] = {}
        self._closed = False
        self._maybe_enable_compile_cache()
        # step telemetry (docs/OBSERVABILITY.md): backend-compile
        # listener (cat="compile" spans + jax_backend_compiles_total)
        # and the opt-in FLAGS_metrics_port sidecar — both idempotent
        # process-wide, so per-Executor construction is free
        _telemetry.install_jax_compile_listener()
        _telemetry.maybe_start_metrics_server()
        # how the LAST run executed: "compiled" | "segmented" |
        # "interpreted" (observability for tests/bench — e.g. the
        # compiled_metric flag in bench.py wide_deep rows)
        self._last_run_mode: Optional[str] = None
        # periodic atomic checkpointing (set_auto_checkpoint /
        # resume_from — docs/FAULT_TOLERANCE.md)
        self._auto_ckpt: Optional[Dict[str, Any]] = None
        # numeric fault plane (FLAGS_check_nan_inf +
        # FLAGS_nan_inf_action): the last step's LAZY device health
        # flag(s), host-side trip counters (only advanced on paths that
        # sync — raise/rollback/profiling), and the rollback monitor
        self._last_health = None
        self._health_stats = {"steps_checked": 0, "trips": 0}
        self._health_monitor: Optional[HealthMonitor] = None
        # True while the just-finished step tripped the guard (only
        # meaningful on synced paths): gates the auto-checkpoint so a
        # snapshot is never taken from inside a fault window — its rng
        # counter would record the DISCARDED step and break the
        # rollback replay's bit-exactness
        self._last_step_tripped = False
        # per-instance override of FLAGS_executor_seg_min_ops (None =
        # use the global). The serving engine pins its private executor
        # to 1 so even tiny stateful programs run their dense chains as
        # compiled segments — an instance attribute, NOT a global flag
        # swap, so a co-resident training executor can never observe it
        self._seg_min_ops_override: Optional[int] = None

    def _build_segmented(self, program, feed, fetch_names, scope, seed,
                         feed_lods) -> Optional[_SegmentedBlock]:
        """Build the segment plan for a block that failed the all-or-
        nothing compiled check. None -> pure interpreter (too few
        compilable ops, or the plan could not be built — the interpreter
        stays the correctness oracle and fallback). Contract violations
        raise exactly like the fused compiled path: KeyError for a data
        var missing from feed= / an unproducible fetch, RuntimeError for
        an uninitialized persistable (startup program not run)."""
        try:
            return _SegmentedBlock(program, tuple(sorted(feed)),
                                   tuple(fetch_names), scope, seed,
                                   feed_lods=feed_lods,
                                   seg_min_ops=self._seg_min_ops_override)
        except _NotSegmentable:
            return None
        except (KeyError, RuntimeError):
            raise  # user errors, not fallback cases
        except Exception as e:  # noqa: BLE001 — any plan failure
            import warnings as _warnings
            _warnings.warn(
                f"segmented compilation unavailable for this program "
                f"({e!r}); falling back to the op-by-op interpreter",
                stacklevel=3)
            return None

    def _maybe_enable_compile_cache(self):
        """Opt-in persistent XLA executable cache: repeated processes
        running the same program skip the compile (the executable loads
        from disk, keyed by HLO hash). Checked at construction AND per
        run — like the dataloader timeout flags, setting
        FLAGS_compilation_cache_dir after the Executor exists must not
        be silently ignored (enable_compile_cache is idempotent per
        dir, so the per-run check is a dict lookup)."""
        cache_dir = core.globals_["FLAGS_compilation_cache_dir"]
        if cache_dir:
            from ..inference import enable_compile_cache
            enable_compile_cache(cache_dir)

    # ------------------------------------------------------------------ API
    def close(self):
        self._closed = True

    # ------------------------------------------- fault-tolerant training
    def set_auto_checkpoint(self, dirname, every_n_steps: int,
                            program=None, scope: Optional[Scope] = None,
                            max_to_keep: int = 3, dataloader=None):
        """Enable periodic atomic checkpoints: every run() whose global
        step counter crosses a multiple of ``every_n_steps`` snapshots
        all persistables (params + optimizer slots) plus the rng fold
        counter to ``dirname/ckpt-<step>`` (io.save_checkpoint — temp
        dir, fsync, rename; a kill mid-save can't corrupt an existing
        checkpoint). ``program``/``scope`` (when given) restrict which
        runs are counted — pass the TRAINING program so startup or eval
        runs don't trigger saves. ``dataloader``: its state_dict() rides
        the manifest so resume can fast-forward the input stream.
        ``every_n_steps <= 0`` disables."""
        if not dirname or every_n_steps <= 0:
            self._auto_ckpt = None
            return
        self._auto_ckpt = {
            "dir": dirname, "every": int(every_n_steps),
            "program": program, "scope": scope,
            "max_to_keep": int(max_to_keep), "dataloader": dataloader,
            "last_step": 0,
        }

    def resume_from(self, path, program=None, scope: Optional[Scope] = None,
                    dataloader=None) -> Optional[Dict[str, Any]]:
        """Restore the newest VALID checkpoint under ``path`` (or that
        exact ckpt dir): parameters, optimizer slot vars, the global rng
        fold counter, and (when ``dataloader`` is passed) the input
        stream position — a killed-and-resumed run then produces
        bit-identical per-step losses to an uninterrupted one (the
        kill-resume parity test in tests/test_fault_tolerance.py).
        Returns the manifest, or None when ``path`` has no checkpoint
        yet (a fresh start — callers can treat both cases uniformly)."""
        from . import io as _io
        if scope is None:
            scope = global_scope()
        if isinstance(path, str) and not os.path.isdir(path):
            return None  # checkpoint root never created: fresh start
        try:
            manifest = _io.load_checkpoint(self, path,
                                           main_program=program,
                                           scope=scope)
        except core.CheckpointError:
            if _io.latest_checkpoint(path) is None and \
                    not os.path.exists(os.path.join(path,
                                                    _io.CKPT_MANIFEST)):
                # nothing restorable: fresh start — loud when ckpt dirs
                # exist but ALL failed validation (vs. a truly empty root)
                if _io._checkpoint_steps(path):
                    import warnings as _warnings
                    _warnings.warn(
                        f"resume_from({path!r}): checkpoints exist but "
                        f"none validated — starting FRESH from step 0",
                        stacklevel=2)
                return None
            raise
        if dataloader is not None and manifest.get("dataloader"):
            dataloader.load_state_dict(manifest["dataloader"])
        if self._auto_ckpt is not None:
            self._auto_ckpt["last_step"] = int(manifest["global_step"])
        return manifest

    def _maybe_auto_checkpoint(self, program, scope: Scope):
        cfg = self._auto_ckpt
        if cfg is None:
            return
        if self._last_step_tripped:
            return  # never checkpoint out of a fault window
        if cfg["program"] is not None and program is not cfg["program"]:
            return
        if cfg["scope"] is not None and scope is not cfg["scope"]:
            return
        step = Executor._rng_counters.get(scope)
        if step is None:
            return
        every = cfg["every"]
        if step // every <= cfg["last_step"] // every:
            return  # no boundary crossed since the last save
        from . import io as _io
        dl = cfg["dataloader"]
        dl_state = (dl.state_dict()
                    if dl is not None and hasattr(dl, "state_dict")
                    else None)
        _io.save_checkpoint(self, cfg["dir"],
                            main_program=cfg["program"] or program,
                            scope=scope, global_step=step,
                            dataloader_state=dl_state,
                            max_to_keep=cfg["max_to_keep"])
        cfg["last_step"] = step

    # ------------------------------------------------ numeric fault plane
    def set_health_monitor(self, ckpt_dir, program=None, scope=None,
                           tolerance=None, max_rollbacks=None,
                           dataloader=None, on_rollback=None
                           ) -> HealthMonitor:
        """Explicitly configure the FLAGS_nan_inf_action=rollback
        monitor (docs/FAULT_TOLERANCE.md "Numeric faults"). Without
        this, the monitor is derived lazily from the auto-checkpoint
        config (set_auto_checkpoint / train_from_dataset
        checkpoint_dir=) on the first tripped step."""
        self._health_monitor = HealthMonitor(
            self, ckpt_dir, program=program, scope=scope,
            tolerance=tolerance, max_rollbacks=max_rollbacks,
            dataloader=dataloader, on_rollback=on_rollback)
        return self._health_monitor

    def _ensure_health_monitor(self, program, scope) -> HealthMonitor:
        if self._health_monitor is not None:
            return self._health_monitor
        cfg = self._auto_ckpt
        if cfg is None or not cfg.get("dir"):
            raise core.NumericFaultError(
                "FLAGS_nan_inf_action=rollback tripped but no checkpoint "
                "plane is configured — call set_auto_checkpoint() (or "
                "pass checkpoint_dir= to train_from_dataset), or wire "
                "set_health_monitor() explicitly")
        self._health_monitor = HealthMonitor(
            self, cfg["dir"], program=cfg["program"] or program,
            scope=cfg["scope"] or scope, dataloader=cfg.get("dataloader"))
        return self._health_monitor

    @staticmethod
    def _offending_segment(cb) -> Optional[str]:
        """Label of the first segment whose fused flag tripped (only
        meaningful for segmented blocks; one host sync per flag — called
        exclusively on the already-tripped slow path)."""
        for label, flag in getattr(cb, "_last_seg_flags", ()) or ():
            if not bool(np.asarray(flag)):
                return label
        return None

    def _localize_and_raise(self, cb, program, scope, rng, step: int):
        """raise-mode tail: the fused health scalar tripped, the select
        kept the pre-step state — re-run the SAME step (same feeds in
        scope, same rng key) through the interpreter, whose per-op
        localizer names the first bad op/var/indices. Segmented blocks:
        island side effects (auc/print) run a second time on this crash
        path — documented in docs/FAULT_TOLERANCE.md."""
        from . import profiler as _profiler
        seg = self._offending_segment(cb)
        _profiler.record_instant(
            f"health:trip[step {step}]", cat="health",
            args={"step": int(step), "action": "raise",
                  "segment": seg or "-"})
        try:
            self._run_block_eager(program.global_block(), scope, rng)
        except FloatingPointError as e:
            raise FloatingPointError(
                f"numeric fault at global step {step}"
                + (f" (first tripped {seg})" if seg else "")
                + f": {e}") from e
        raise core.NumericFaultError(
            f"health guard tripped at global step {step}"
            + (f" in {seg}" if seg else "")
            + " but the interpreter re-run reproduced no non-finite op "
            "output — the fault did not replay (e.g. a poisoned feed "
            "replaced since, or island-stateful nondeterminism)")

    def _process_health(self, cb, program, scope, health, step0: int,
                        n_steps: int, rng=None):
        """Post-step policy dispatch over the fused health flag(s).
        skip (and AMP-only) stays LAZY — no host sync unless the
        profiler wants trip markers; raise and rollback read the flags
        back (that sync is those actions' documented cost)."""
        if not cb._guard_active:
            return
        self._last_health = health
        from . import profiler as _profiler
        # trip markers need a host readback of the flags — only a real
        # profiler session pays it; FLAGS_trace_dir shard streaming
        # must not re-add the per-step sync skip-mode avoids
        profiling = _profiler.is_session()
        action = cb._guard_action if cb._guard_check else None
        if action not in ("raise", "rollback") and not profiling:
            return
        flags = np.asarray(health).reshape(-1).astype(bool)
        self._health_stats["steps_checked"] += len(flags)
        n_bad = int((~flags).sum())
        self._health_stats["trips"] += n_bad
        if action in ("raise", "rollback"):
            # sticky across the steps of ONE run (segmented/window
            # loops): any tripped step gates this run's auto-checkpoint
            # — a rollback target must never come from inside a fault
            # window. ONLY policy-bearing actions set it: skip always
            # syncs here only when profiling, and observability must
            # not change checkpoint cadence (a skip-discarded step
            # leaves clean state, so snapshotting it is valid).
            self._last_step_tripped = self._last_step_tripped \
                or bool(n_bad)
        if action == "raise":
            if n_bad:
                bad = int(np.flatnonzero(~flags)[0])
                if rng is None:
                    # no single-step rng context (mesh window path) —
                    # surface typed instead of mis-localizing
                    raise core.NumericFaultError(
                        f"numeric fault at global step {step0 + bad} "
                        f"(windowed mesh run — re-run per-step for the "
                        f"op-level localization)")
                self._localize_and_raise(cb, program, scope, rng,
                                         step0 + bad)
            return
        if action == "rollback":
            mon = self._health_monitor
            for i, ok_ in enumerate(flags):
                if ok_:
                    if mon is not None:
                        mon.observe(True, step0 + i)
                    continue
                if mon is None:
                    mon = self._ensure_health_monitor(program, scope)
                if mon.observe(False, step0 + i) == "rolled_back":
                    # flags past the restore describe discarded compute
                    break
            return
        if n_bad and profiling:  # skip / AMP-only: markers, no policy
            seg = self._offending_segment(cb)
            for i in np.flatnonzero(~flags):
                _profiler.record_instant(
                    f"health:trip[step {step0 + int(i)}]", cat="health",
                    args={"step": int(step0 + int(i)),
                          "action": action or "amp",
                          "segment": seg or "-"})

    def health_stats(self) -> Dict[str, int]:
        """Host-side guard counters. Only paths that sync (raise/
        rollback/profiling) advance them — skip mode is deliberately
        sync-free; read ``_last_health`` (device) for its verdicts."""
        return dict(self._health_stats)

    def _interp_guard_cfg(self, program, feed_names, scope):
        """The interpreter oracle's guard plan, mirroring
        _CompiledBlock._init_guard's state classification so compiled
        and interpreted runs reduce health over the SAME variable set
        (the AMP bit-parity contract). None when the fault plane is
        off."""
        check = bool(core.globals_["FLAGS_check_nan_inf"])
        amp = getattr(program, "_amp_dynamic", None)
        if not check and amp is None:
            return None
        action = str(core.globals_["FLAGS_nan_inf_action"])
        if check and action not in _GUARD_ACTIONS:
            raise ValueError(
                f"FLAGS_nan_inf_action={action!r} is not one of "
                f"{sorted(_GUARD_ACTIONS)}")
        # the classification is invariant per (program version, feeds,
        # scope, flags) — cache ON the program (dies with it, like
        # _prune_cache; the scope weakref guards id reuse), mirroring
        # the compiled path's classify-once-at-build semantics instead
        # of re-walking every op each interpreted step
        ckey = (program._version, tuple(sorted(feed_names)), check,
                action)
        cache = program.__dict__.setdefault("_interp_guard_cache", {})
        hit = cache.get(ckey)
        if hit is not None and hit[0]() is scope:
            return hit[1]
        block = program.global_block()
        ops = [op for op in block.ops if op.type not in ("feed", "fetch")]
        if amp is not None and not _block_reads_amp_scale(ops, amp):
            amp = None  # pruned-away machinery: same rule as _init_guard
        if not check and amp is None:
            cache[ckey] = (weakref.ref(scope), None)
            return None

        def _ok(n):
            return _initialized_tensor(scope, n) is not None

        written: set = set()
        rbw: List[str] = []
        for op in ops:
            for name in op.input_arg_names:
                if name in written or name in feed_names or name in rbw:
                    continue
                if _ok(name):
                    rbw.append(name)
            written.update(_effective_writes(op))
        persistable = {v.name for v in block.vars.values()
                       if v.persistable}
        amp_names = (set() if amp is None else
                     {amp["scale"], amp["good"], amp["bad"]})
        sel = [n for n in rbw if n in written and n not in amp_names]
        for n in sorted(written):
            if (n in persistable and n not in sel
                    and n not in feed_names and n not in amp_names
                    and _ok(n)):
                sel.append(n)
        cfg = {"check": check, "action": action, "amp": amp,
               "select_names": tuple(sel),
               # same health source as the compiled epilogue: param
               # grads (+ fetches), falling back to all grads then to
               # the written state
               "health_names": tuple(
                   n + GRAD_SUFFIX for n in sel
                   if n + GRAD_SUFFIX in written) or tuple(
                   n for n in sorted(written)
                   if n.endswith(GRAD_SUFFIX)),
               "select": amp is not None or (
                   check and action in ("skip", "rollback"))}
        cache[ckey] = (weakref.ref(scope), cfg)
        return cfg

    def _run_interpreted_step(self, program, scope, rng, guard,
                              fetch_names) -> bool:
        """One eager step + the numeric-fault epilogue (same health
        set, same select/AMP arithmetic as the compiled epilogue — the
        interpreter is the oracle the compiled guard is tested
        against). raise-mode localization fires PER OP inside
        _run_op_eager, so a bad op raises mid-step with full detail;
        skip/rollback restore the pre-step state refs (jax arrays are
        immutable, so the snapshot is free). Returns the step's health
        verdict (True when unguarded)."""
        block = program.global_block()
        if guard is None:
            self._run_block_eager(block, scope, rng)
            return True
        from .ir import fused_health

        def _val(n):
            return _initialized_tensor(scope, n)
        snap = {}
        if guard["select"]:
            for n in guard["select_names"]:
                t = _val(n)
                if t is not None:
                    snap[n] = (t.array, t.lod())
        self._run_block_eager(block, scope, rng)
        vals = []
        for n in guard["health_names"]:
            t = _val(n)
            if t is not None:
                vals.append(t.array)
        if not vals:
            for n in guard["select_names"]:
                t = _val(n)
                if t is not None:
                    vals.append(t.array)
        for n in fetch_names or ():
            t = _val(n)
            if t is not None:
                vals.append(t.array)
        health = fused_health(vals)
        healthy = bool(np.asarray(health))
        if guard["amp"] is not None and not (
                guard["check"] and guard["action"] == "raise"
                and not healthy):
            # same rule as _apply_discard: under raise a tripped step
            # keeps its pre-step scale/counters (the localizer replay
            # must see the exact overflow-producing scale)
            a = guard["amp"]
            new_scale, new_good, new_bad = _amp_scale_update(
                health, _val(a["scale"]).array, _val(a["good"]).array,
                _val(a["bad"]).array, a)
            scope.var(a["scale"]).set_value(LoDTensor(new_scale))
            scope.var(a["good"]).set_value(LoDTensor(new_good))
            scope.var(a["bad"]).set_value(LoDTensor(new_bad))
        self._last_health = health
        self._health_stats["steps_checked"] += 1
        if guard["check"] and guard["action"] in ("raise", "rollback"):
            # same rule as _process_health: only policy-bearing actions
            # gate the auto-checkpoint
            self._last_step_tripped = self._last_step_tripped \
                or not healthy
        if not healthy:
            self._health_stats["trips"] += 1
            if guard["select"]:
                for n, (arr, lod) in snap.items():
                    scope.var(n).set_value(LoDTensor(arr, lod))
        if guard["check"] and guard["action"] == "rollback":
            step = Executor._rng_counters.get(scope, 1) - 1
            mon = self._health_monitor
            if not healthy and mon is None:
                mon = self._ensure_health_monitor(program, scope)
            if mon is not None:
                mon.observe(healthy, step)
        return healthy

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, feed_var_name="feed", fetch_var_name="fetch",
            scope: Optional[Scope] = None, return_numpy: bool = True,
            use_program_cache: bool = False, use_prune: bool = False,
            mesh=None, param_shardings=None, n_steps: int = 1):
        """reference executor.py:457 Executor.run. ``n_steps > 1`` runs
        that many steps with the SAME feeds as one dispatched lax.scan
        on the compiled path (fetches come back stacked [n_steps, ...]);
        per-dispatch host/tunnel overhead amortizes to a single dispatch
        — the benchmark/training-loop shape. Interpreted programs run
        the steps sequentially and return the final fetch values."""
        from .compiler import CompiledProgram
        from . import profiler as _profiler
        if _profiler.is_profiling() and _telemetry.current_trace() is None:
            # trace correlation (docs/OBSERVABILITY.md): one root trace
            # per run() — every span this step records (segments,
            # windows, the PS round's rpc calls and their pserver
            # handler spans) shares one trace id, which is what makes a
            # training round followable trainer→pserver in the merged
            # cluster timeline. Serving/batch callers that already
            # installed a context keep theirs.
            with _telemetry.trace_scope():
                return self.run(
                    program=program, feed=feed, fetch_list=fetch_list,
                    feed_var_name=feed_var_name,
                    fetch_var_name=fetch_var_name, scope=scope,
                    return_numpy=return_numpy,
                    use_program_cache=use_program_cache,
                    use_prune=use_prune, mesh=mesh,
                    param_shardings=param_shardings, n_steps=n_steps)
        self._maybe_enable_compile_cache()
        if program is None:
            program = default_main_program()
        if isinstance(program, CompiledProgram):
            return program._run(self, feed, fetch_list, scope, return_numpy,
                                mesh=mesh, param_shardings=param_shardings,
                                n_steps=n_steps)
        if scope is None:
            scope = global_scope()
        feed = feed or {}
        fetch_names = _to_fetch_names(fetch_list)
        # stale trip verdicts must not gate THIS run's auto-checkpoint
        self._last_step_tripped = False

        if use_prune and fetch_names:
            # backward-slice to the fetch targets (reference executor.py
            # _prune_program + prune cache keyed like the run cache). Note
            # the reference caveat applies: pruning a training program by
            # its loss drops the optimizer ops.
            # cache lives ON the program object (not keyed by id()), so it
            # dies with the program and a recycled id can never serve a
            # stale pruned copy
            pkey = (program._version, tuple(fetch_names))
            cache = program.__dict__.setdefault("_prune_cache", {})
            pruned = cache.get(pkey)
            if pruned is None:
                pruned = cache[pkey] = program._prune(list(fetch_names))
            program = pruned

        # a WindowBatch (DataLoader.window) knows its own window length —
        # forgetting n_steps=k must not silently broadcast the [K, ...]
        # stack as one giant step
        window_names: Tuple[str, ...] = ()
        wk = getattr(feed, "k", None)
        if isinstance(wk, int) and wk > 0:
            if n_steps == 1:
                n_steps = wk
            elif n_steps != wk:
                raise ValueError(
                    f"feed is a WindowBatch of {wk} stacked batches but "
                    f"n_steps={n_steps} was requested")
            # every WindowBatch entry is K stacked real batches by
            # construction, so slicing is always correct — no rank
            # heuristic (which would silently BROADCAST the stack for a
            # var it cannot classify, e.g. a concrete-first-dim var)
            window_names = tuple(feed)
        elif feed and n_steps > 1:
            # raw dict feeds: a leading [n_steps, ...] dim means n_steps
            # DISTINCT batches consumed one slice per step; empty tuple
            # = the same-feeds broadcast degenerate case. Detection only
            # engages for an explicit multi-step request — a plain
            # n_steps=1 dict run keeps the pre-window semantics for
            # rank-polymorphic feeds and skips the per-feed var scan on
            # the hot path.
            window_names = _window_feed_names(program, feed, n_steps)

        mode = core.globals_["FLAGS_executor_mode"]
        compiled_ok = (mode == "compiled"
                       and _ops_compilable(program.global_block().ops))

        if window_names and not compiled_ok:
            # Documented per-step fallback for windowed feeds on paths
            # where the window cannot collapse to one dispatch:
            # segmented blocks (islands have per-step host side
            # effects) and interpreted blocks. Same contract as the
            # compiled window: step i consumes slice i of every
            # windowed feed, rng advances one global step per slice,
            # fetches come back stacked [n_steps, ...]. Decided BEFORE
            # the feed upload below — the whole [K, ...] stack must not
            # be device_put just to be re-uploaded slice by slice.
            # Compiled MESH programs scan the window like the 1-device
            # path since the 3D lane work: the stack is device_put ONCE
            # with its batch dim (dim 1) sharded over "dp" and the
            # window dim left whole for the scan — pipeline-sectioned
            # programs consume DataLoader window stacks directly, the
            # microbatch slices carved on-device inside the scanned
            # step.
            return self._run_window_fallback(
                program, feed, fetch_list, scope, return_numpy, mesh,
                param_shardings, n_steps, window_names)

        if (n_steps > 1 or window_names) and compiled_ok \
                and core.globals_["FLAGS_check_nan_inf"] \
                and core.globals_["FLAGS_nan_inf_action"] == "raise":
            # raise is the DEBUGGING action: the offending step must
            # re-run through the interpreter localizer from exactly its
            # pre-step state, so windows take the documented per-step
            # fallback instead of one fused scan. Decided BEFORE the
            # feed upload below, like the fallback above — the [K, ...]
            # stack must not be device_put just to be re-uploaded slice
            # by slice.
            return self._run_window_fallback(
                program, feed, fetch_list, scope, return_numpy, mesh,
                param_shardings, n_steps, window_names)

        # materialize program vars' metadata for persistables (create slots)
        # feeds → device
        use_feed_cache = core.globals_["FLAGS_feed_device_cache"]
        feed_arrays = {}
        feed_lods = {}
        for name, data in feed.items():
            t = (self._feed_device_cached(name, data)
                 if use_feed_cache else None)
            if t is None:
                t = _as_lodtensor(data, self.place)
            scope.var(name).set_value(t)
            feed_arrays[name] = t.array
            lv = _normalize_lod(t.lod())
            if lv:
                feed_lods[name] = lv
        # segmented compilation (default when the all-or-nothing check
        # fails): jitted islands of pure ops around interpreted stateful
        # ops, instead of interpreting the WHOLE block. Mesh runs keep
        # their existing paths (compiled or interpreted).
        try_segmented = (not compiled_ok and mode == "compiled"
                         and mesh is None
                         and core.globals_["FLAGS_executor_segmentation"])

        cb = None
        if compiled_ok or try_segmented:
            key = (id(program), program._version, tuple(sorted(feed)),
                   tuple(fetch_names), id(scope),
                   tuple(sorted(feed_lods.items())),
                   # the numeric fault guard is BAKED into the trace —
                   # flipping its flags rebuilds the program instead of
                   # silently running an unguarded (or stale-action)
                   # executable
                   (core.globals_["FLAGS_check_nan_inf"],
                    core.globals_["FLAGS_nan_inf_action"]),
                   None if mesh is None else
                   (tuple(mesh.shape.items()), tuple(map(id, mesh.devices.flat))),
                   None if not param_shardings else
                   tuple(sorted((k, str(v))
                                for k, v in param_shardings.items())))
            cached = self._compiled_cache.get(key)
            # guard id() reuse: a dead scope's id can be recycled by a new
            # scope with different state — every cache entry (including
            # the "interpreted" unprofitable-key marker) validates a scope
            # weakref before being trusted
            cb, rebuild = None, True
            if isinstance(cached, tuple):  # ("interpreted", scope_ref)
                if cached[1]() is scope:
                    rebuild = False  # known unprofitable for this scope
            elif cached is not None and cached._scope_ref() is scope:
                cb, rebuild = cached, False
            if rebuild:
                # static-analysis choke point (docs/ANALYSIS.md): verify
                # ONCE per program version at its first compile, BEFORE
                # tracing — a structural defect gets a diagnostic with a
                # fix hint instead of a deep TracerError. An error-level
                # failure caches nothing, so a retry re-verifies.
                _analysis.maybe_verify(
                    program, "executor", feed_names=tuple(sorted(feed)),
                    fetch_names=tuple(fetch_names),
                    param_shardings=param_shardings, scope=scope)
                seed = (program.random_seed
                        or core.globals_["FLAGS_seed"])
                if compiled_ok:
                    cb = _CompiledBlock(program, tuple(sorted(feed)),
                                        tuple(fetch_names), scope, seed,
                                        mesh=mesh,
                                        param_shardings=param_shardings,
                                        feed_lods=feed_lods)
                else:
                    cb = self._build_segmented(
                        program, feed, fetch_names, scope, seed,
                        feed_lods)
                if cb is not None and cb.kind == "segmented":
                    # donation-safety cross-check against the plan the
                    # segmented build ACTUALLY produced (own dedup key:
                    # the plan exists only post-build)
                    _analysis.maybe_verify(
                        program, "executor-plan",
                        feed_names=tuple(sorted(feed)),
                        fetch_names=tuple(fetch_names),
                        segment_plan=cb.segments, scope=scope)
                self._compiled_cache[key] = (
                    cb if cb is not None
                    else ("interpreted", weakref.ref(scope)))

        if cb is not None and cb.kind == "compiled":
            if n_steps > 1 or window_names:
                rng_base, idx0 = self._next_rng_window(scope, program,
                                                       n_steps)
                fetched, health = cb.run_window(scope, feed_arrays,
                                                rng_base, idx0, n_steps,
                                                window_names)
                self._process_health(cb, program, scope, health, idx0,
                                     n_steps)
            else:
                rng = self._next_rng(scope, program)
                fetched, health = cb.run(scope, feed_arrays, rng)
                self._process_health(
                    cb, program, scope, health,
                    Executor._rng_counters.get(scope, 1) - 1, 1, rng=rng)
            fetch_lods = cb.fetch_lods
            self._last_run_mode = "compiled"
        elif cb is not None:  # segmented: host loop per step (islands
            # have per-step side effects); final step's fetches returned,
            # the interpreter contract
            fetched, fetch_lods = [], []
            for _ in range(n_steps):
                rng = self._next_rng(scope, program)
                fetched, fetch_lods, health = cb.run_step(
                    scope, feed_arrays, rng, self)
                self._process_health(
                    cb, program, scope, health,
                    Executor._rng_counters.get(scope, 1) - 1, 1, rng=rng)
            self._last_run_mode = "segmented"
        else:
            # interpreted programs have no compile event — the analysis
            # choke point anchors on the once-per-version guard-config
            # build instead (maybe_verify dedups by program version)
            _analysis.maybe_verify(
                program, "executor", feed_names=tuple(sorted(feed)),
                fetch_names=tuple(fetch_names), scope=scope)
            guard = self._interp_guard_cfg(program, set(feed), scope)
            for _ in range(n_steps - 1):  # same feeds, repeated steps
                rng = self._next_rng(scope, program)
                self._run_interpreted_step(program, scope, rng, guard,
                                           fetch_names)
            rng = self._next_rng(scope, program)
            self._run_interpreted_step(program, scope, rng, guard,
                                       fetch_names)
            self._last_run_mode = "interpreted"
            fetched = []
            fetch_lods = []
            for n in fetch_names:
                v = scope.find_var(n)
                if v is None:
                    raise KeyError(f"fetch var '{n}' not found in scope")
                val = v.value()
                if isinstance(val, LoDTensor):
                    fetched.append(val.array)
                    fetch_lods.append(_normalize_lod(val.lod()))
                else:
                    fetched.append(val)
                    fetch_lods.append(None)

        # periodic atomic checkpoint AFTER the step's state writeback —
        # the snapshot sees exactly the post-step scope
        self._maybe_auto_checkpoint(program, scope)

        if fetch_names and return_numpy:
            return [_restore_fetch_dtype(program, n, _fetch_to_host(f))
                    for n, f in zip(fetch_names, fetched)]
        if fetch_names:
            # LoDTensor fetches stay LAZY device arrays (the async
            # training-loop contract — no per-step sync); only a
            # non-addressable multi-process global must gather here. The
            # int64-restore policy applies at np conversion, i.e. on the
            # return_numpy=True path.
            return [LoDTensor(f if not (isinstance(f, jax.Array)
                                        and not f.is_fully_addressable)
                              else _fetch_to_host(f), lod=lv)
                    for f, lv in zip(fetched, fetch_lods)]
        return []

    # ------------------------------------------------------ dataset path
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None, mesh=None, window_size=1,
                           checkpoint_dir=None,
                           checkpoint_every_n_steps=0, resume_from=None):
        """One pass over a Dataset (reference: executor.py:1438
        train_from_dataset → C++ MultiTrainer/HogwildWorker threads,
        trainer.h:64). The TPU inversion: batches stream from the native
        C++ feed engine into the ONE jitted step — XLA pipelining replaces
        the reference's per-thread op loops. ``mesh``: a device mesh for
        the step; with a "pp" axis, a PipelineOptimizer-sectioned program
        runs stage-parallel (the SectionWorker/PipelineTrainer role —
        section_worker.cc:142 — via fluid/pipeline_lowering.py).
        ``window_size=K``: stack K consecutive dense same-shape batches
        into one [K, ...]-windowed run (ONE dispatch on the compiled
        path — docs/INPUT_PIPELINE.md); batches that carry LoD or ragged
        shapes run per-step as before.

        ``checkpoint_dir`` + ``checkpoint_every_n_steps``: enable
        periodic atomic checkpoints for this training program (see
        set_auto_checkpoint); ``resume_from``: restore the newest valid
        checkpoint under that path first (see resume_from) — together
        they make a killed-and-relaunched dataset run continue with
        bit-identical rng streams (docs/FAULT_TOLERANCE.md)."""
        if program is None:
            program = default_main_program()
        if checkpoint_dir and checkpoint_every_n_steps > 0:
            self.set_auto_checkpoint(checkpoint_dir,
                                     checkpoint_every_n_steps,
                                     program=program, scope=scope)
        if resume_from:
            self.resume_from(resume_from, program=program, scope=scope)
        return self._run_from_dataset(program, dataset, scope, fetch_list,
                                      fetch_info, print_period,
                                      fetch_handler, mesh=mesh,
                                      window_size=window_size)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None, mesh=None, window_size=1):
        return self._run_from_dataset(program, dataset, scope, fetch_list,
                                      fetch_info, print_period,
                                      fetch_handler, mesh=mesh,
                                      window_size=window_size)

    @staticmethod
    def _stack_dataset_window(feeds: List[Dict[str, Any]]):
        """[{name: LoDTensor}] * K → WindowBatch of [K, ...] arrays when
        every value is LoD-free and shapes match across the window; None
        otherwise (the caller falls back to per-step runs). Same
        assembly contract as DataLoader.window (reader._stack_window),
        just non-raising."""
        from .reader import _stack_window
        try:
            return _stack_window(feeds, len(feeds), len(feeds))
        except (ValueError, KeyError):
            return None

    def _run_from_dataset(self, program, dataset, scope, fetch_list,
                          fetch_info, print_period, fetch_handler=None,
                          mesh=None, window_size=1):
        if dataset is None:
            raise ValueError("dataset must be provided")
        if program is None:
            program = default_main_program()
        if scope is None:
            scope = global_scope()
        dataset._ensure_handle()
        if dataset.get_memory_data_size() == 0:
            dataset._load()
        fetch_names = _to_fetch_names(fetch_list)
        monitor = None
        if fetch_handler is not None:
            monitor = _FetchHandlerMonitor(scope, fetch_handler)
            monitor.start()
        step = 0
        last = []

        def report(vals, count=1):
            # fire once per print_period: when a period boundary falls
            # in [step, step + count) — per-step runs (count=1) print
            # exactly at multiples of print_period like before, windows
            # print once per crossed boundary (labelled by the window's
            # first global step; the value is the window's final step)
            if not (fetch_names and print_period):
                return
            off = step % print_period
            if off != 0 and off + count <= print_period:
                return
            infos = fetch_info or fetch_names
            msg = ", ".join(
                f"{i}={np.asarray(v).reshape(-1)[-1]:.6f}"
                for i, v in zip(infos, vals))
            print(f"[train_from_dataset] step {step}: {msg}")

        pending: List[Dict[str, Any]] = []

        def flush():
            nonlocal step, last
            if not pending:
                return
            # _stack_dataset_window returns a WindowBatch, which run()
            # treats as windowed WHOLESALE (no rank heuristic that could
            # silently broadcast an unclassifiable var's stack)
            stacked = (self._stack_dataset_window(pending)
                       if len(pending) > 1 else None)
            if stacked is not None:
                last = self.run(program, feed=stacked,
                                fetch_list=fetch_list, scope=scope,
                                mesh=mesh, n_steps=len(pending))
                # report BEFORE advancing: the label is the window's
                # first global step (matching per-step mode's step 0
                # baseline row)
                report(last, count=len(pending))
                step += len(pending)
            else:
                for f in pending:
                    last = self.run(program, feed=f,
                                    fetch_list=fetch_list, scope=scope,
                                    mesh=mesh)
                    report(last)
                    step += 1
            pending.clear()

        try:
            for feed in dataset._iter_batches():
                pending.append(feed)
                if len(pending) >= max(1, window_size):
                    flush()
            flush()
        finally:
            if monitor is not None:
                monitor.stop()
        return last

    # --------------------------------------------------------------- eager
    _fold_rng = None  # class-level jitted fold: one dispatch per step
    _rng_counters = weakref.WeakKeyDictionary()  # scope -> host step count

    def _advance_rng_counter(self, scope: Scope, n: int) -> int:
        # the step counter is a host int per scope (a device round-trip per
        # step costs ~0.4ms of pure overhead); the scope var mirrors it for
        # inspection, stored as a lazy numpy buffer
        cnt = Executor._rng_counters.get(scope)
        if cnt is None:
            v = scope.var("@RNG_COUNTER@")
            cnt = (int(np.asarray(v.get_tensor().array).reshape(-1)[0])
                   if v.is_initialized() else 0)
        Executor._rng_counters[scope] = cnt + n
        scope.var("@RNG_COUNTER@").set_value(
            LoDTensor(np.asarray([cnt + n], np.int32)))
        return cnt

    def _program_seed(self, program: Program) -> int:
        return int(program.random_seed or core.globals_["FLAGS_seed"])

    def _next_rng(self, scope: Scope, program: Program):
        # the fold is jitted once so deriving the step key is one cached
        # dispatch
        cnt = self._advance_rng_counter(scope, 1)
        seed = self._program_seed(program)
        if Executor._fold_rng is None:
            Executor._fold_rng = jax.jit(
                lambda s, c: jax.random.fold_in(jax.random.key(s), c))
        if getattr(self, "_seed_cache", None) is None or \
                self._seed_cache[0] != seed:
            self._seed_cache = (seed, jnp.int32(seed))
        return Executor._fold_rng(self._seed_cache[1], np.int32(cnt))

    def _next_rng_window(self, scope: Scope, program: Program,
                         n_steps: int):
        """Base key + starting global step index for a windowed run. The
        counter advances by n_steps, so the per-step keys the scan body
        derives — fold_in(key(seed), idx0 + i) — are EXACTLY the keys
        n_steps sequential single-step run() calls would draw."""
        cnt = self._advance_rng_counter(scope, n_steps)
        seed = self._program_seed(program)
        if getattr(self, "_base_key_cache", None) is None or \
                self._base_key_cache[0] != seed:
            self._base_key_cache = (seed, jax.random.key(seed))
        return self._base_key_cache[1], cnt

    def _run_window_fallback(self, program, feed, fetch_list, scope,
                             return_numpy, mesh, param_shardings, n_steps,
                             window_names):
        """Per-step loop with the windowed-run CONTRACT (slice i per
        step, one global rng step per slice, stacked fetches) for paths
        where one-dispatch scanning is unavailable — see the call site
        in run(). Each step re-enters run() with n_steps=1, so the
        per-path semantics (segment islands, interpreter, mesh
        placement) are exactly the sequential-loop ones."""
        from . import profiler as _profiler
        from . import async_overlap as _ao
        # sparse prefetch (docs/PS_DATA_PLANE.md "Async overlap"): with
        # the overlap plane on, window i+1's embedding ids are staged to
        # the prefetch thread BEFORE step i dispatches — its deduped
        # row fan-out runs while step i computes, and step i+1's
        # distributed_lookup_table consumes the buffered rows without
        # an RPC (the row-cache consult hook).
        plane = _ao.maybe_plane()
        plan = _ao.prefetch_plan(program) if plane is not None else ()

        def _slice(name, i):
            v = feed[name]
            a = v.array if isinstance(v, LoDTensor) else v
            return a[i]

        def _stage(i):
            for table, ids_name, eps in plan:
                if ids_name in window_names and ids_name in feed:
                    plane.stage(table, np.asarray(_slice(ids_name, i)),
                                list(eps))

        ctx = (_profiler.RecordEvent(f"window[{n_steps}]:fallback",
                                     cat="window")
               if _profiler.is_profiling() else contextlib.nullcontext())
        per_step = []
        with ctx:
            for i in range(n_steps):
                if plan and i + 1 < n_steps:
                    _stage(i + 1)
                f = {}
                for n, v in feed.items():
                    if n in window_names:
                        a = v.array if isinstance(v, LoDTensor) else v
                        f[n] = a[i]
                    else:
                        f[n] = v
                per_step.append(self.run(
                    program, feed=f, fetch_list=fetch_list, scope=scope,
                    return_numpy=return_numpy, mesh=mesh,
                    param_shardings=param_shardings))
        if not per_step or not per_step[0]:
            return per_step[-1] if per_step else []
        n_fetch = len(per_step[0])
        if return_numpy:
            return [np.stack([s[k] for s in per_step])
                    for k in range(n_fetch)]
        stacked = []
        for k in range(n_fetch):
            if any(s[k].lod() for s in per_step):
                raise NotImplementedError(
                    "windowed run cannot stack LoD-carrying fetches — "
                    "fetch dense vars or run per-step (n_steps=1)")
            stacked.append(
                LoDTensor(jnp.stack([s[k].array for s in per_step])))
        return stacked

    # feeds above this size pay more for the content scan than the
    # device_put it could skip; they always re-upload
    _FEED_CACHE_MAX_BYTES = 4 << 20
    # a name whose identity keeps changing (fresh dataloader array each
    # step) stops being fingerprinted after this many straight misses
    _FEED_CACHE_MISS_LIMIT = 8

    @staticmethod
    def _feed_fingerprint(a: np.ndarray) -> Optional[int]:
        """Content fingerprint: CRC32 over the raw buffer — POSITION-
        SENSITIVE, so the common in-place mutations (row shuffles,
        element swaps) that a plain word-sum misses are detected. C
        speed, no copy for contiguous buffers."""
        if not a.flags.c_contiguous:
            return None
        import zlib
        return zlib.crc32(a.view(np.uint8).reshape(-1).data)

    def _feed_device_cached(self, name: str, data) -> Optional[LoDTensor]:
        """Identity+content-keyed feed→device cache
        (FLAGS_feed_device_cache, ON by default): when the SAME ndarray
        object (same buffer address) is fed again AND its CRC32 matches
        the upload-time value, reuse the device array and skip the
        per-step device_put — the dominant host cost of a small training
        step. The stored array object is pinned, so the CRC must be
        captured at upload time (a later in-place mutation changes the
        shared buffer). Names fed a fresh array every step stop paying
        the scan after a short miss streak."""
        if not isinstance(data, np.ndarray) \
                or data.nbytes > Executor._FEED_CACHE_MAX_BYTES:
            return None
        cache = getattr(self, "_feed_cache", None)
        if cache is None:
            cache = self._feed_cache = {}
        entry = cache.get(name)
        if entry == "uncacheable":
            return None
        prefix = (id(data), data.__array_interface__["data"][0],
                  data.shape, data.dtype.str)
        fp = Executor._feed_fingerprint(data)
        if fp is None:
            return None
        if entry is not None and entry[0] == prefix and fp == entry[1]:
            entry[4][0] = 0
            return entry[3]
        if entry is not None and entry[0] != prefix:
            misses = entry[4]
            misses[0] += 1
            if misses[0] >= Executor._FEED_CACHE_MISS_LIMIT:
                cache[name] = "uncacheable"
                return None
        else:
            misses = [0]
        t = _as_lodtensor(data, self.place)
        # pin the source ndarray: while the entry lives, its id/buffer
        # address cannot be recycled by a new array (which would
        # otherwise falsely hit this prefix)
        cache[name] = (prefix, fp, data, t, misses)
        return t

    def _run_block_eager(self, block, scope: Scope, rng_base,
                         check_nan: Optional[bool] = None):
        """``check_nan``: None infers the per-op localizer from the
        flags (raise mode only — skip/rollback get the end-of-step
        fused check instead); True forces it regardless of action.
        listen_and_serv forces it for pserver optimize blocks, which
        run OUTSIDE Executor.run and would otherwise lose all guarding
        under skip/rollback (the server has no step epilogue — raising
        back to the trainer is its containment)."""
        for idx, op in enumerate(block.ops):
            self._run_op_eager(op, scope, rng_base, idx,
                               check_nan=check_nan)

    def _run_op_eager(self, op, scope: Scope, rng_base, idx: int = 0,
                      check_nan: Optional[bool] = None):
        from . import profiler as _profiler
        if _profiler.is_profiling():
            # per-op host span (reference operator.cc:948-977 RecordEvent
            # hooks around prepare/infer_shape/compute)
            with _profiler.RecordEvent(op.type):
                return self._run_op_eager_impl(op, scope, rng_base, idx,
                                               check_nan)
        return self._run_op_eager_impl(op, scope, rng_base, idx,
                                       check_nan)

    def _run_op_eager_impl(self, op, scope: Scope, rng_base, idx: int = 0,
                           check_nan: Optional[bool] = None):
        otype = op.type
        stateful = _op_is_stateful(op)
        attrs = op.attrs
        if stateful:
            if not OPS.has(otype):
                raise NotImplementedError(f"op '{otype}' is not implemented")
            info = OPS.get(otype)
            attrs = dict(attrs)
            attrs["_ctx"] = ExecContext(scope, self, op, self.place, rng_base)
            if info.needs_rng:
                attrs["_rng"] = jax.random.fold_in(rng_base, idx)
        ins = {}
        for slot, names in op.inputs.items():
            vals = []
            for n in names:
                v = scope.find_var(n)
                if v is None or not v.is_initialized():
                    vals.append(None)
                elif isinstance(v.value(), LoDTensor):
                    vals.append(v.value().array)
                else:
                    vals.append(None)  # stateful kernels read scope directly
            ins[slot] = vals

        def _scope_lod(n):
            v = scope.find_var(n)
            if v is not None and v.is_initialized() and isinstance(
                    v.value(), LoDTensor):
                return _normalize_lod(v.value().lod())
            return None
        in_lods = _collect_in_lods(op, _scope_lod)
        if _op_needs_lod(op):
            attrs = dict(attrs)
            attrs["_lod"] = in_lods
        if OPS.has(otype):
            info = OPS.get(otype)
            if info.needs_rng and "_rng" not in attrs:
                attrs = dict(attrs)
                if attrs.get("fix_seed", False) or attrs.get("seed", 0):
                    attrs["_rng"] = jax.random.key(int(attrs.get("seed", 0)))
                else:
                    attrs["_rng"] = jax.random.fold_in(rng_base, idx)
            outs = info.kernel(ins, attrs)
        elif otype.endswith("_grad") and OPS.has(otype[:-5]):
            base = OPS.get(otype[:-5])
            if base.needs_rng:
                attrs = dict(attrs)
                attrs["_rng"] = jax.random.fold_in(
                    rng_base, int(attrs.get("_fwd_idx", idx)))
            outs = run_generic_grad(
                otype[:-5], ins, attrs,
                wanted_grad_slots=list(op.outputs.keys()),
                fwd_input_slots=op.attrs.get("_fwd_in", list(op.inputs.keys())))
        elif otype.endswith("_grad_grad") and OPS.has(otype[:-10]):
            from ..ops.registry import run_generic_grad_grad
            if OPS.get(otype[:-10]).needs_rng:
                attrs = dict(attrs)
                attrs["_rng"] = jax.random.fold_in(
                    rng_base, int(attrs.get("_fwd_idx", idx)))
            outs = run_generic_grad_grad(
                otype[:-10], ins, attrs,
                wanted_grad_slots=list(op.outputs.keys()),
                gradop_slots=op.attrs.get("_fwd_in",
                                          list(op.inputs.keys())))
        else:
            raise NotImplementedError(f"op '{otype}' is not implemented")
        if check_nan is None:
            check_nan = (core.globals_["FLAGS_check_nan_inf"]
                         and core.globals_["FLAGS_nan_inf_action"]
                         == "raise")
        if check_nan:
            # raise-mode per-op localizer; skip/rollback use the
            # end-of-step fused health instead (no per-op host syncs)
            _check_op_outputs_finite(op, idx, outs)
        for slot, names in op.outputs.items():
            vals = (outs or {}).get(slot)
            if vals is None:
                continue
            for n, v in zip(names, vals):
                if v is not None and n != "@EMPTY@":
                    scope.var(n).set_value(LoDTensor(v))

        def _set_scope_lod(n, lv):
            v = scope.find_var(n)
            if v is not None and v.is_initialized() and isinstance(
                    v.value(), LoDTensor):
                v.value().set_lod([list(l) for l in lv] if lv else [])

        def _scope_len(n):
            v = scope.find_var(n)
            if (v is not None and v.is_initialized()
                    and isinstance(v.value(), LoDTensor)
                    and getattr(v.value().array, "ndim", 0)):
                return v.value().array.shape[0]
            return None
        _propagate_lods(op, outs, in_lods, _set_scope_lod, _scope_len)


def _check_op_outputs_finite(op, idx: int, outs) -> None:
    """raise-mode localizer (interpreter path). ONE device fetch per op:
    each float output contributes a fused ``isfinite().all()`` flag and
    the stacked flags cross to host together — the reference pays one
    blocking device→host copy PER OUTPUT (nan_inf_utils_detail.cc
    CheckVarHasNanOrInf), and so did this port before. On a trip the
    slow path re-walks the outputs and names the op index/type, output
    slot, var name, dtype, NaN/Inf counts, and the first offending flat
    indices — the FloatingPointError message the raise action exists
    for."""
    flat = []  # (slot, var name, value)
    for slot, vals in (outs or {}).items():
        if slot.startswith("_"):  # "_lod"-style metadata, not tensors
            continue
        names = op.outputs.get(slot) or []
        for k, v in enumerate(vals or []):
            if v is not None and hasattr(v, "dtype") \
                    and jnp.issubdtype(v.dtype, jnp.inexact):
                flat.append((slot,
                             names[k] if k < len(names) else f"[{k}]", v))
    if not flat:
        return
    flags = jnp.stack([jnp.all(jnp.isfinite(v)) for _, _, v in flat])
    host_flags = np.asarray(flags)  # the ONE host sync for this op
    if host_flags.all():
        return
    problems = []
    for ok_, (slot, name, v) in zip(host_flags, flat):
        if ok_:
            continue
        arr = np.asarray(v)
        bad = np.flatnonzero(~np.isfinite(arr.reshape(-1)))[:8].tolist()
        problems.append(
            f"output {slot} (var '{name}', dtype {arr.dtype}, shape "
            f"{tuple(arr.shape)}): {int(np.isnan(arr).sum())} NaN / "
            f"{int(np.isinf(arr).sum())} Inf, first offending flat "
            f"indices {bad}")
    raise FloatingPointError(
        f"NaN/Inf in output of op #{idx} '{op.type}': "
        + "; ".join(problems))


def _fetch_to_host(f) -> np.ndarray:
    """Fetched value → host numpy. In multi-process runs a fetched global
    array spans non-addressable devices: replicated values read the local
    copy, sharded values gather across processes (the reference pulls
    fetches to trainer rank over gRPC — operators/distributed; here the
    collective rides jax's runtime)."""
    if isinstance(f, jax.Array) and not f.is_fully_addressable:
        if f.sharding.is_fully_replicated:
            return np.asarray(f.addressable_data(0))
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(f, tiled=True))
    return np.asarray(f)


def _restore_fetch_dtype(program, name: str, arr: np.ndarray) -> np.ndarray:
    """Device integers are 32-bit by policy (core._to_device_array); widen
    a fetched int32/uint32 back to the program-declared 64-bit dtype so
    user-visible numpy matches the reference op contracts."""
    if arr.dtype not in (np.int32, np.uint32):
        return arr
    try:
        v = program.global_block()._find_var_recursive(name)
    except Exception:
        return arr
    want = getattr(v, "dtype", None) if v is not None else None
    if want is None:
        return arr
    try:  # var dtype may be a string ("int64") or a VarType enum
        np_want = np.dtype(want) if isinstance(want, str) \
            else np.dtype(core.dtype_to_np(want))
    except Exception:
        return arr
    if np_want in (np.dtype(np.int64), np.dtype(np.uint64)):
        return arr.astype(np_want)
    return arr


def _to_fetch_names(fetch_list) -> List[str]:
    names = []
    if fetch_list is None:
        return names
    if not isinstance(fetch_list, (list, tuple)):
        fetch_list = [fetch_list]
    for f in fetch_list:
        if isinstance(f, Variable):
            names.append(f.name)
        elif isinstance(f, str):
            names.append(f)
        elif isinstance(f, (list, tuple)):
            names.extend(_to_fetch_names(f))
        else:
            raise TypeError(f"bad fetch entry {f!r}")
    return names
