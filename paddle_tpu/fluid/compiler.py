"""CompiledProgram / BuildStrategy / ExecutionStrategy (reference:
python/paddle/fluid/compiler.py:87, pybind BuildStrategy pybind.cc:1946).

Inversion: the reference's ``with_data_parallel`` builds a multi-device SSA
graph with allreduce op-handles (ParallelExecutor). Here data parallelism is
sharding metadata: the executor jits the step under a ``jax.sharding.Mesh``
with the batch sharded over the data axis — XLA inserts the grad all-reduces
over ICI. BuildStrategy knobs that tune NCCL/fusion behaviour are accepted
and recorded (XLA already fuses; hierarchical allreduce is automatic)."""
from __future__ import annotations

from typing import Optional

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


class _ReduceStrategy:
    AllReduce = 0
    Reduce = 1


class _GradientScaleStrategy:
    CoeffNumDevice = 0
    One = 1
    Customized = 2


class BuildStrategy:
    ReduceStrategy = _ReduceStrategy
    GradientScaleStrategy = _GradientScaleStrategy

    def __init__(self):
        self.reduce_strategy = _ReduceStrategy.AllReduce
        self.gradient_scale_strategy = _GradientScaleStrategy.CoeffNumDevice
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_optimizer_ops = False
        self.fuse_all_reduce_ops = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_relu_depthwise_conv = False
        self.fuse_broadcast_ops = False
        self.sync_batch_norm = False
        self.enable_sequential_execution = False
        self.remove_unnecessary_lock = True
        self.num_trainers = 1
        self.trainer_id = 0
        self.trainers_endpoints = []
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0
        self.nccl_comm_num = 1
        self.cache_runtime_context = False
        self.debug_graphviz_path = ""


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1
        self.allow_op_delay = False
        self.use_thread_barrier = True
        # segmented compilation for blocks with stateful/host ops (jitted
        # islands around interpreted ops — fluid/executor.py
        # _SegmentedBlock). False pins such blocks to the pure op-by-op
        # interpreter, the correctness oracle.
        self.allow_mixed_compilation = True


class CompiledProgram:
    """reference compiler.py:87."""

    def __init__(self, program_or_graph, build_strategy: Optional[BuildStrategy] = None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = None
        self._is_data_parallel = False
        self._loss_name = None
        self._share_vars_from = None
        self._places = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._share_vars_from = share_vars_from
        self._places = places
        return self

    def _apply_build_strategy_passes(self, scope, fetch_list=None):
        """Run the ir passes the BuildStrategy flags select (reference
        BuildStrategy::Apply, parallel_executor.cc:575). Fusion patterns
        whose intermediates feed grad ops simply don't match, so this is
        safe on programs that already carry backward ops. Each call's fetch
        vars are protected from fusion; if a later call fetches an
        intermediate the first application fused away, the pipeline is
        re-applied from the pristine program with the union of fetch
        sets (fusion can't be undone in place)."""
        fetch_names = set()
        for f in fetch_list or ():
            fetch_names.add(f if isinstance(f, str) else f.name)
        if getattr(self, "_bs_passes_applied", False):
            prev = getattr(self, "_bs_protected", set())
            if fetch_names <= prev:
                return
            # restore the pre-pass program and redo with the union
            self._program = self._bs_pristine.clone()
            fetch_names |= prev
        else:
            self._bs_pristine = self._program.clone()
        self._bs_passes_applied = True
        self._bs_protected = set(fetch_names)
        names = []
        bs = self._build_strategy
        if bs.fuse_elewise_add_act_ops:
            names.append("fuse_elewise_add_act_pass")
        if bs.fuse_bn_act_ops:
            names.append("fuse_bn_act_pass")
        if bs.debug_graphviz_path:
            names.append("graph_viz_pass")
        if not names:
            return
        from .ir import PassManager
        pm = PassManager(names, scope=scope)
        if bs.debug_graphviz_path:
            for p in pm.passes:
                if p.name == "graph_viz_pass":
                    p.set("graph_viz_path", bs.debug_graphviz_path)
        self._program = pm.apply(self._program, protected=fetch_names)

    def _run(self, executor, feed, fetch_list, scope, return_numpy,
             mesh=None, param_shardings=None, n_steps=1):
        """Delegate to the executor. Data-parallel execution shards the feed
        batch over the device mesh (see parallel/data_parallel.py); on a
        single chip this is a plain jitted run. ``n_steps``/windowed feeds
        (a leading [K, ...] dim of distinct batches — docs/INPUT_PIPELINE.md)
        ride through to Executor.run untouched. The with_data_parallel
        wrapper rejects an explicit n_steps>1 (its per-run sharding
        protocol is single-step); a WindowBatch fed through it reaches
        the executor, which takes the documented per-step mesh fallback —
        for one-dispatch scanned windows pass mesh= to a plain
        Executor.run."""
        self._apply_build_strategy_passes(scope, fetch_list)
        if self._exec_strategy is not None and \
                not self._exec_strategy.allow_mixed_compilation:
            from .core import globals_ as _g
            prev = _g["FLAGS_executor_segmentation"]
            _g["FLAGS_executor_segmentation"] = False
            try:
                return self._run_impl(executor, feed, fetch_list, scope,
                                      return_numpy, mesh, param_shardings,
                                      n_steps)
            finally:
                _g["FLAGS_executor_segmentation"] = prev
        return self._run_impl(executor, feed, fetch_list, scope,
                              return_numpy, mesh, param_shardings, n_steps)

    def _run_impl(self, executor, feed, fetch_list, scope, return_numpy,
                  mesh, param_shardings, n_steps):
        if self._is_data_parallel:
            from ..parallel.data_parallel import run_data_parallel
            if n_steps != 1:
                raise NotImplementedError(
                    "n_steps > 1 with CompiledProgram.with_data_parallel "
                    "is not supported — pass mesh= to a plain Executor.run "
                    "for scanned multi-step windows")
            if mesh is not None:
                # an explicit mesh (e.g. dp×mp) overrides the auto-built
                # 1-axis dp mesh; cached for subsequent steps
                self._mesh = mesh
            return run_data_parallel(executor, self, feed, fetch_list, scope,
                                     return_numpy,
                                     param_shardings=param_shardings)
        return executor.run(self._program, feed=feed, fetch_list=fetch_list,
                            scope=scope, return_numpy=return_numpy,
                            mesh=mesh, param_shardings=param_shardings,
                            n_steps=n_steps)
