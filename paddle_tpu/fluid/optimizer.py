"""Optimizers (reference: python/paddle/fluid/optimizer.py — Optimizer:55,
SGD:842, Momentum:936, Adagrad:1598, Adam:1714, Adamax:1980, Dpsgd:2152,
DecayedAdagrad:2247, Adadelta:2357, RMSProp:2476, Ftrl:2664, Lamb:2823,
LarsMomentum:1484, ModelAverage:2995, ExponentialMovingAverage:3302,
RecomputeOptimizer:3850, LookaheadOptimizer:4138, PipelineOptimizer:3550).

``minimize`` = append_backward + regularization + grad clip + one update op
per parameter — identical contract to the reference. On TPU the whole
optimizer pass lives inside the jitted step, so "fuse_all_optimizer_ops"
style passes are unnecessary: XLA fuses them.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import core, unique_name
from .backward import append_backward, OP_ROLE_OPTIMIZE
from .clip import append_gradient_clip_ops
from .core import VarDesc
from .framework import (Parameter, Program, Variable, default_main_program,
                        default_startup_program, in_dygraph_mode,
                        program_guard)
from .initializer import Constant
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops

__all__ = [
    "SGD", "Momentum", "Adagrad", "Adam", "Adamax", "Dpsgd", "DecayedAdagrad",
    "Ftrl", "SGDOptimizer", "MomentumOptimizer", "AdagradOptimizer",
    "AdamOptimizer", "AdamaxOptimizer", "DpsgdOptimizer",
    "DecayedAdagradOptimizer", "RMSPropOptimizer", "FtrlOptimizer", "Adadelta",
    "AdadeltaOptimizer", "ModelAverage", "LarsMomentum",
    "LarsMomentumOptimizer", "LambOptimizer", "ExponentialMovingAverage",
    "PipelineOptimizer", "LookaheadOptimizer", "RecomputeOptimizer",
    "DGCMomentumOptimizer", "DGCMomentum", "Lookahead", "Lamb",
    "GradientMergeOptimizer",
]


class Optimizer:
    """Base (reference optimizer.py:55)."""

    def __init__(self, learning_rate, parameter_list=None,
                 regularization=None, name=None, grad_clip=None):
        self._learning_rate = learning_rate
        self._parameter_list = parameter_list
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._learning_rate_map: Dict[int, Variable] = {}
        self._accumulators: Dict[str, Dict[str, Variable]] = defaultdict(dict)
        self.helper = None
        self.type = getattr(self, "type", "sgd")

    # ------------------------------------------------------------- lr
    def _create_global_learning_rate(self):
        if in_dygraph_mode():
            if not hasattr(self, "_dygraph_lr_var"):
                from .dygraph.base import VarBase
                import jax.numpy as jnp
                lr = self._learning_rate
                if callable(lr) and not isinstance(lr, Variable):
                    lr = lr()
                val = lr.array if hasattr(lr, "array") else float(lr)
                self._dygraph_lr_var = VarBase(
                    jnp.asarray(val, jnp.float32).reshape(1),
                    stop_gradient=True)
            return
        program = default_main_program()
        lr = self._learning_rate_map.get(id(program))
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[id(program)] = self._learning_rate
            return
        lr_name = unique_name.generate("learning_rate")
        lr_var = program.global_block().create_var(
            name=lr_name, shape=(1,), persistable=True,
            dtype=VarDesc.VarType.FP32)
        lr_var.stop_gradient = True
        startup = default_startup_program().global_block()
        sv = startup.create_var(name=lr_name, shape=(1,), persistable=True,
                                dtype=VarDesc.VarType.FP32)
        Constant(float(self._learning_rate))(sv, startup)
        self._learning_rate_map[id(program)] = lr_var

    def _global_learning_rate(self, program=None):
        if in_dygraph_mode():
            return self._dygraph_lr_var
        if program is None:
            program = default_main_program()
        return self._learning_rate_map.get(id(program))

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        base = self._global_learning_rate()
        plr = (param.optimize_attr or {}).get("learning_rate", 1.0)
        if plr == 1.0:
            return base
        from .layers import nn as _nn
        return _nn._act_layer("scale", base, {"scale": float(plr)})

    # ----------------------------------------------------- accumulators
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        if in_dygraph_mode():
            from .dygraph.base import VarBase
            import jax.numpy as jnp
            from .core import dtype_to_jnp
            shp = shape if shape is not None else param.shape
            acc = VarBase(jnp.full([int(s) for s in shp], float(fill_value),
                                   dtype_to_jnp(dtype or param.dtype)),
                          stop_gradient=True, persistable=True)
            self._accumulators[name][param.name] = acc
            return acc
        block = default_main_program().global_block()
        var_name = unique_name.generate(param.name + "_" + name)
        shape = shape if shape is not None else param.shape
        var = block.create_var(name=var_name, shape=shape, persistable=True,
                               dtype=dtype or param.dtype,
                               belong_to_optimizer=True)
        var.stop_gradient = True
        startup = default_startup_program().global_block()
        sv = startup.create_var(name=var_name, shape=shape, persistable=True,
                                dtype=dtype or param.dtype)
        Constant(float(fill_value))(sv, startup)
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # ------------------------------------------------------------- api
    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, parameters_and_grads):
        pass

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        if in_dygraph_mode():
            from .dygraph.base import _dygraph_backward
            return _dygraph_backward(self, loss, parameter_list
                                     or self._parameter_list)
        return append_backward(loss, parameter_list or self._parameter_list,
                               no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        if self._grad_clip is not None:
            params_grads = [self._grad_clip._process(p, g) if g is not None
                            else (p, g) for p, g in params_grads] \
                if not hasattr(self._grad_clip, "_process_group") \
                else self._grad_clip._process_group(params_grads)
        else:
            params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        return self._create_optimization_pass(params_grads)

    def apply_optimize(self, loss, startup_program, params_grads):
        with program_guard(default_main_program(),
                           startup_program or default_startup_program()):
            return self.apply_gradients(params_grads)

    def _create_optimization_pass(self, parameters_and_grads):
        # current (not global) block: GradientMergeOptimizer places the
        # whole update inside a conditional_block sub-block
        block = default_main_program().current_block()
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(
            block, [p for p, g in parameters_and_grads if g is not None])
        ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            if param_and_grad[0].trainable:
                op = self._append_optimize_op(block, param_and_grad)
                if hasattr(op, "attrs"):
                    op.attrs["op_role"] = OP_ROLE_OPTIMIZE
                    op.attrs["op_role_var"] = [param_and_grad[0].name,
                                               param_and_grad[1].name]
                ops.append(op)
        self._finish_update(block, parameters_and_grads)
        return ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        if in_dygraph_mode():
            from .dygraph.base import _dygraph_minimize
            return _dygraph_minimize(self, loss, startup_program,
                                     parameter_list or self._parameter_list,
                                     no_grad_set)
        with program_guard(loss.block.program,
                           startup_program or default_startup_program()):
            params_grads = self.backward(loss, startup_program,
                                         parameter_list, no_grad_set)
            optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # dygraph helpers
    def set_dict(self, state_dict):
        self._dygraph_state = dict(state_dict)

    def state_dict(self):
        return getattr(self, "_dygraph_state", {})

    def current_step_lr(self):
        lr = self._learning_rate
        return float(lr) if not isinstance(lr, Variable) else lr

    def clear_gradients(self):
        from .dygraph.base import _clear_gradients
        _clear_gradients(self._parameter_list)


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, parameter_list=None,
                 regularization=None, name=None, grad_clip=None):
        super().__init__(learning_rate, parameter_list, regularization, name,
                         grad_clip)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type="sgd",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]]})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, parameter_list=None,
                 use_nesterov=False, regularization=None, name=None,
                 grad_clip=None):
        super().__init__(learning_rate, parameter_list, regularization, name,
                         grad_clip)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator("velocity", param_and_grad[0])
        return block.append_op(
            type="momentum",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "VelocityOut": [velocity]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov})


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameter_list=None,
                 regularization=None, name=None, grad_clip=None):
        super().__init__(learning_rate, parameter_list, regularization, name,
                         grad_clip)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator("velocity", param_and_grad[0])
        return block.append_op(
            type="lars_momentum",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameter_list=None,
                 regularization=None, name=None, initial_accumulator_value=0.0,
                 grad_clip=None):
        super().__init__(learning_rate, parameter_list, regularization, name,
                         grad_clip)
        self.type = "adagrad"
        self._epsilon = epsilon
        self.initial_accumulator_value = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p,
                                  fill_value=self.initial_accumulator_value)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator("moment", param_and_grad[0])
        return block.append_op(
            type="adagrad",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameter_list=None, regularization=None,
                 name=None, lazy_mode=False, grad_clip=None):
        super().__init__(learning_rate, parameter_list, regularization, name,
                         grad_clip)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = lazy_mode

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1
                                  if not isinstance(self._beta1, Variable)
                                  else 0.9, shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2
                                  if not isinstance(self._beta2, Variable)
                                  else 0.999, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        m1 = self._get_accumulator("moment1", param_and_grad[0])
        m2 = self._get_accumulator("moment2", param_and_grad[0])
        b1p = self._get_accumulator("beta1_pow_acc", param_and_grad[0])
        b2p = self._get_accumulator("beta2_pow_acc", param_and_grad[0])
        return block.append_op(
            type="adam",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "lazy_mode": self._lazy_mode})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameter_list=None, regularization=None,
                 name=None, grad_clip=None):
        super().__init__(learning_rate, parameter_list, regularization, name,
                         grad_clip)
        self.type = "adamax"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator("moment", param_and_grad[0])
        inf_norm = self._get_accumulator("inf_norm", param_and_grad[0])
        b1p = self._get_accumulator("beta1_pow_acc", param_and_grad[0])
        op = block.append_op(
            type="adamax",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [moment], "InfNorm": [inf_norm],
                    "Beta1Pow": [b1p],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment],
                     "InfNormOut": [inf_norm]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})
        # scale beta1^t (reference appends scale op per step)
        block.append_op(type="scale", inputs={"X": [b1p]},
                        outputs={"Out": [b1p]},
                        attrs={"scale": self._beta1,
                               "op_role": OP_ROLE_OPTIMIZE})
        return op


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, clip=0.9, batch_size=0.999,
                 sigma=1e-8, parameter_list=None):
        super().__init__(learning_rate, parameter_list)
        self.type = "dpsgd"
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type="dpsgd",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]]},
            attrs={"clip": self._clip, "batch_size": self._batch_size,
                   "sigma": self._sigma})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 parameter_list=None, regularization=None, name=None,
                 grad_clip=None):
        super().__init__(learning_rate, parameter_list, regularization, name,
                         grad_clip)
        self.type = "decayed_adagrad"
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator("moment", param_and_grad[0])
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 parameter_list=None, regularization=None, name=None,
                 grad_clip=None):
        super().__init__(learning_rate, parameter_list, regularization, name,
                         grad_clip)
        self.type = "adadelta"
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("__avg_squared_grad", p)
            self._add_accumulator("__avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        g = self._get_accumulator("__avg_squared_grad", param_and_grad[0])
        u = self._get_accumulator("__avg_squared_update", param_and_grad[0])
        return block.append_op(
            type="adadelta",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "AvgSquaredGrad": [g], "AvgSquaredUpdate": [u]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "AvgSquaredGradOut": [g], "AvgSquaredUpdateOut": [u]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameter_list=None, regularization=None,
                 name=None, grad_clip=None):
        super().__init__(learning_rate, parameter_list, regularization, name,
                         grad_clip)
        self.type = "rmsprop"
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        mom = self._get_accumulator("momentum", param_and_grad[0])
        ms = self._get_accumulator("mean_square", param_and_grad[0])
        mg = self._get_accumulator("mean_grad", param_and_grad[0])
        return block.append_op(
            type="rmsprop",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [mom], "MeanSquare": [ms], "MeanGrad": [mg],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [mom],
                     "MeanSquareOut": [ms], "MeanGradOut": [mg]},
            attrs={"epsilon": self._epsilon, "decay": self._rho,
                   "momentum": self._momentum, "centered": self._centered})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameter_list=None, regularization=None, name=None,
                 grad_clip=None):
        super().__init__(learning_rate, parameter_list, regularization, name,
                         grad_clip)
        self.type = "ftrl"
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        sq = self._get_accumulator("squared", param_and_grad[0])
        lin = self._get_accumulator("linear", param_and_grad[0])
        return block.append_op(
            type="ftrl",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "SquaredAccumulator": [sq], "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "SquaredAccumOut": [sq], "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


class LambOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameter_list=None,
                 regularization=None, exclude_from_weight_decay_fn=None,
                 name=None, grad_clip=None):
        super().__init__(learning_rate=learning_rate, beta1=beta1,
                         beta2=beta2, epsilon=epsilon,
                         parameter_list=parameter_list,
                         regularization=regularization, name=name,
                         grad_clip=grad_clip)
        self.type = "lamb"
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, param_and_grad):
        m1 = self._get_accumulator("moment1", param_and_grad[0])
        m2 = self._get_accumulator("moment2", param_and_grad[0])
        b1p = self._get_accumulator("beta1_pow_acc", param_and_grad[0])
        b2p = self._get_accumulator("beta2_pow_acc", param_and_grad[0])
        wd = 0.0 if (self._exclude_fn is not None
                     and self._exclude_fn(param_and_grad[0])) \
            else self._weight_decay
        return block.append_op(
            type="lamb",
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "weight_decay": wd})


class ModelAverage(Optimizer):
    """reference optimizer.py:2995 — kept as API; apply/restore via
    accumulated param sums."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization=regularization, name=name)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        raise NotImplementedError(
            "ModelAverage: pending (round-2 aux-optimizer batch)")


class ExponentialMovingAverage:
    def __init__(self, decay=0.999, thres_steps=None, name=None):
        raise NotImplementedError(
            "ExponentialMovingAverage: pending (round-2 aux-optimizer batch)")


class PipelineOptimizer:
    """Pipeline-parallel training (reference optimizer.py:3550).

    The reference splits the program into sections at ``cut_list`` variables
    and hands them to `PipelineTrainer`/`SectionWorker` threads that move
    scopes through blocking queues (reference: pipeline_trainer.cc:24,
    section_worker.cc:142). Here the split is the same — contiguous op
    sections bounded at the producer of each cut variable — and the section
    metadata is attached to the program as ``program._pipeline_opt``.

    Execution semantics: `Executor.run(..., mesh=<pp mesh>)` lowers the
    sectioned program onto the compiled GPipe schedule
    (`fluid/pipeline_lowering.py` → `parallel.pipeline.gpipe`: shard_map
    over the "pp" mesh axis, `lax.ppermute` stage transfers over ICI,
    backward via the vjp's transposed ring) when the interior sections
    are homogeneous; anything else — and runs without a pp mesh —
    executes as one fused compiled step with a warning (numerically
    identical to pipelined execution; pipelining is a throughput
    schedule, not a semantic change). Queue-runtime knobs (`queue_size`,
    `concurrency_list`, `start_cpu_core_id`) have no compiled equivalent
    and are recorded but inert; ``sync_steps`` maps to the microbatch
    count of the schedule.
    """

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0):
        self._optimizer = optimizer
        self._cut_list = cut_list or []
        self._place_list = place_list or []
        self._concurrency_list = concurrency_list or []
        self._queue_size = queue_size
        self._sync_steps = sync_steps

    def _cut_var_names(self):
        names = []
        for group in self._cut_list:
            items = group if isinstance(group, (list, tuple)) else [group]
            for v in items:
                names.append(v.name if hasattr(v, "name") else str(v))
        return names

    def _split_program(self, program):
        """Section i = ops [bounds[i], bounds[i+1]); a section ends right
        after the op that first produces a cut variable (mirrors reference
        optimizer.py:3550 section extraction)."""
        block = program.global_block()
        producer = {}
        for i, op in enumerate(block.ops):
            for n in op.output_arg_names:
                producer.setdefault(n, i)
        cuts = sorted({producer[n] + 1 for n in self._cut_var_names()
                       if n in producer})
        bounds = [0] + cuts + [len(block.ops)]
        return [list(range(bounds[i], bounds[i + 1]))
                for i in range(len(bounds) - 1)
                if bounds[i] < bounds[i + 1]]

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        optimize_ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        program = loss.block.program
        sections = self._split_program(program)
        # params owned by a section = params read by its ops (stage placement)
        block = program.global_block()
        pnames = {p.name for p, _ in params_grads}
        section_params = []
        seen = set()
        for sec in sections:
            # owner stage of a param = the section that FIRST reads it (its
            # forward use); backward/optimizer ops reading it later stay on
            # the owner stage, matching reference section placement
            used = []
            for i in sec:
                for n in block.ops[i].input_arg_names:
                    if n in pnames and n not in seen:
                        seen.add(n)
                        used.append(n)
            section_params.append(used)
        program._pipeline_opt = {
            "sections": sections,
            "section_params": section_params,
            "cut_vars": self._cut_var_names(),
            "num_microbatches": max(1, self._sync_steps),
            "place_list": list(self._place_list),
            "concurrency_list": list(self._concurrency_list),
            "queue_size": self._queue_size,
        }
        return optimize_ops, params_grads


class RecomputeOptimizer(Optimizer):
    """reference optimizer.py:3850 — rematerialization. The checkpoint
    var names are recorded on the program (``_recompute_opt``) and the
    compiled executor lowers the segments between them onto
    ``jax.checkpoint`` + vjp span replacement
    (fluid/recompute_lowering.py): activations inside a segment are
    recomputed in the backward instead of stored, so only segment
    boundaries stay live between forward and backward. Non-lowerable
    shapes execute without remat (same numerics, more memory), with a
    warning."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        callbacks)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self._optimizer.apply_optimize(loss, startup_program,
                                              params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_optimize(loss, startup_program, params_grads)
        if self._checkpoints:
            names = [v.name if hasattr(v, "name") else str(v)
                     for v in self._checkpoints]
            loss.block.program._recompute_opt = {"checkpoints": names}
        return optimize_ops, params_grads


class GradientMergeOptimizer:
    """Gradient accumulation over ``k_steps`` micro-batches (the reference's
    batch-merge capability: ir/multi_batch_merge_pass.cc replicates the
    forward/backward k times and merges gradients; tests
    test_dist_mnist_batch_merge.py). Here the accumulate lives in the main
    block and the parameter update sits in a conditional_block that fires
    every k-th step — on TPU everything stays inside ONE jitted computation
    and XLA lowers the conditional to a predicated update.

    API follows the reference line's GradientMerge optimizer:
    ``GradientMergeOptimizer(inner, k_steps=4, avg=True).minimize(loss)``.
    """

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if int(k_steps) < 1:
            raise ValueError("k_steps should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = bool(avg)
        self.type = "gradient_merge"

    def _create_persistable(self, main_block, startup, name, shape, dtype,
                            value):
        v = main_block.create_var(name=name, shape=shape, dtype=dtype,
                                  persistable=True)
        v.stop_gradient = True
        sb = startup.global_block()
        sv = sb.create_var(name=name, shape=shape, dtype=dtype,
                           persistable=True)
        Constant(float(value))(sv, sb)
        return v

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .layers import control_flow, tensor
        from .layers import nn as lnn
        main = loss.block.program
        startup = startup_program or default_startup_program()
        k = self.k_steps
        with program_guard(main, startup):
            params_grads = self.inner_optimizer.backward(
                loss, startup_program, parameter_list, no_grad_set)
            block = main.global_block()
            step = self._create_persistable(
                block, startup, unique_name.generate("gradient_merge_step"),
                [1], "int32", 0)
            control_flow.increment(step, value=1, in_place=True)
            merged = []
            for p, g in params_grads:
                if g is None:
                    continue
                acc = self._create_persistable(
                    block, startup,
                    unique_name.generate(p.name + "@GradientMerge"),
                    p.shape, p.dtype, 0.0)
                block.append_op(type="elementwise_add",
                                inputs={"X": [acc.name], "Y": [g.name]},
                                outputs={"Out": [acc.name]},
                                attrs={"axis": -1})
                merged.append((p, acc))

            if k == 1:
                cond_var = None
            else:
                k_var = tensor.fill_constant([1], "int32", k)
                zero = tensor.fill_constant([1], "int32", 0)
                cond_var = control_flow.equal(
                    lnn.elementwise_mod(step, k_var), zero)

            optimize_ops = []

            def _apply():
                new_pg = []
                for p, acc in merged:
                    g = acc
                    if self.avg:
                        g = lnn.scale(acc, scale=1.0 / k)
                    new_pg.append((p, g))
                optimize_ops.extend(
                    self.inner_optimizer.apply_gradients(new_pg))
                for p, acc in merged:
                    # reset the accumulator for the next k-step window
                    main.current_block().append_op(
                        type="scale", inputs={"X": [acc.name]},
                        outputs={"Out": [acc.name]},
                        attrs={"scale": 0.0, "bias": 0.0,
                               "bias_after_scale": True})

            if cond_var is None:
                _apply()
            else:
                control_flow.cond(cond_var, _apply)
        return optimize_ops, params_grads


class LookaheadOptimizer:
    """reference optimizer.py:4138 — slow weights track fast weights every
    k steps: slow += alpha * (fast - slow); fast := slow. Implemented as
    extra graph ops gated on a step counter (k is compiled in; XLA folds
    the cond into a select)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)

    def minimize(self, loss, startup_program=None):
        import paddle_tpu.fluid.layers as L
        mini_out = self.inner_optimizer.minimize(
            loss, startup_program=startup_program)
        main = loss.block.program
        startup = startup_program or default_startup_program()
        with program_guard(main, startup):
            self._append_lookahead_ops(main, startup, L)
        return mini_out

    def _append_lookahead_ops(self, main, startup, L):
        block = main.global_block()
        # step counter (integer: an fp32 counter stops incrementing at 2^24)
        step = block.create_var(name=unique_name.generate("lookahead_step"),
                                shape=(1,), persistable=True,
                                dtype=VarDesc.VarType.INT64)
        sv = startup.global_block().create_var(
            name=step.name, shape=(1,), persistable=True,
            dtype=VarDesc.VarType.INT64)
        Constant(0)(sv, startup.global_block())
        block.append_op(type="increment", inputs={"X": [step]},
                        outputs={"Out": [step]}, attrs={"step": 1.0})
        # every k steps blend slow/fast
        kmod = L.elementwise_mod(step, L.fill_constant([1], "int64", self.k))
        is_sync = L.cast(L.equal(kmod, L.fill_constant([1], "int64", 0)),
                         "float32")
        for param in main.all_parameters():
            slow = block.create_var(
                name=unique_name.generate(param.name + "_slow"),
                shape=param.shape, persistable=True, dtype=param.dtype)
            ssv = startup.global_block().create_var(
                name=slow.name, shape=param.shape, persistable=True,
                dtype=param.dtype)
            # slow starts equal to the param's init
            startup.global_block().append_op(
                type="assign", inputs={"X": [param.name]},
                outputs={"Out": [ssv]})
            blended = L.elementwise_add(
                slow, L.elementwise_mul(
                    L.elementwise_sub(param, slow),
                    L.fill_constant([1], "float32", self.alpha)))
            new_slow = L.elementwise_add(
                L.elementwise_mul(blended, is_sync),
                L.elementwise_mul(slow, 1.0 - is_sync))
            new_fast = L.elementwise_add(
                L.elementwise_mul(blended, is_sync),
                L.elementwise_mul(param, 1.0 - is_sync))
            block.append_op(type="assign", inputs={"X": [new_slow]},
                            outputs={"Out": [slow]})
            block.append_op(type="assign", inputs={"X": [new_fast]},
                            outputs={"Out": [param]})


class DGCMomentumOptimizer(MomentumOptimizer):
    """reference optimizer.py:1071 — deep gradient compression momentum.
    The reference top-k sparsifies grads to save NCCL bandwidth
    (operators/dgc_op.cc + SparseAllReduceOpHandle). On TPU the grad
    reduction rides ICI inside the jitted step where bandwidth is not the
    bottleneck, so this optimizer preserves the API/momentum semantics and
    the rampup knobs; compression itself is intentionally a no-op (the
    reference behavior below rampup_begin_step)."""

    def __init__(self, learning_rate, momentum, rampup_begin_step,
                 rampup_step=1, sparsity=None, parameter_list=None,
                 use_nesterov=False, local_grad_clip_norm=None,
                 num_trainers=None, regularization=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, momentum,
                         parameter_list=parameter_list,
                         use_nesterov=use_nesterov,
                         regularization=regularization,
                         grad_clip=grad_clip, name=name)
        self._rampup_begin_step = rampup_begin_step
        self._rampup_step = rampup_step
        self._sparsity = sparsity or [0.999]


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Dpsgd = DpsgdOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
LarsMomentum = LarsMomentumOptimizer
Lamb = LambOptimizer
DGCMomentum = DGCMomentumOptimizer
Lookahead = LookaheadOptimizer
