"""Lower a PipelineOptimizer-sectioned fluid program onto the compiled
GPipe schedule.

The reference executes sectioned programs through a thread/queue runtime
(reference: python/paddle/fluid/optimizer.py:3550 PipelineOptimizer,
paddle/fluid/framework/section_worker.cc:142, pipeline_trainer.cc:24).
The TPU inversion compiles the schedule instead: the homogeneous interior
sections become ONE `parallel.pipeline.gpipe` call (shard_map over the
"pp" mesh axis, lax.ppermute stage handoff) embedded in the executor's
single jitted step, and the interior's backward ops are replaced by the
`jax.vjp` of that call — the ppermute transposes run the reverse
pipeline. Pre ops (up to the first cut), post/loss/optimizer ops and
every non-interior gradient still execute on the normal traced path, so
feeds, state donation, fetches and the optimizer all work unchanged.

Lowering preconditions (checked by `build_plan`; anything else falls
back to the fused path with a warning — numerically identical, just not
stage-parallel):
  * mesh has a "pp" axis whose size == number of interior sections
  * interior sections are homogeneous: same op types/attrs positionally,
    stage-varying inputs have matching shapes (params stack)
  * interior ops are batch-row-independent (no batch_norm/data_norm),
    rng-free (dropout inside a stage would draw per-stage masks the
    fused oracle can't mirror), and sub-block-free
  * the microbatch count divides the feed batch
"""
from __future__ import annotations

import warnings
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from .backward import grad_var_name

# ops whose output for one batch row depends on other rows — microbatch
# splitting changes their semantics, so the interior may not contain them
_BATCH_MIXING = {"batch_norm", "sync_batch_norm", "data_norm"}


class PipelinePlan:
    def __init__(self):
        self.pre_ops = []           # ops up to and incl. the c0 producer
        self.template_ops = []      # section-1 ops (the stage body)
        self.post_ops = []          # post fwd + loss + post bwd
        self.tail_ops = []          # pre bwd + optimizer updates
        self.n_stages = 0
        self.n_micro = 1
        self.c0 = None              # activation entering the interior
        self.c_last = None          # activation leaving the interior
        self.template_out = None    # template name of the stage output
        self.closure_names = []     # externals shared by every stage
        self.param_template = []    # template name per stacked position
        self.param_stage_names = []  # per position: [stage0.., stageN-1..]


def _op_signature(op):
    attrs = {k: v for k, v in op.attrs.items()
             if not k.startswith("_") and k != "op_role"}
    return (op.type, sorted(attrs.items(), key=lambda kv: kv[0]))


def _fallback(reason):
    warnings.warn(
        f"PipelineOptimizer program not lowerable onto the gpipe "
        f"schedule ({reason}); executing fused (numerically identical, "
        f"not stage-parallel)", stacklevel=3)
    return None


def build_plan(cb, popt) -> Optional[PipelinePlan]:
    """cb: the _CompiledBlock being built. Returns a PipelinePlan or None
    (fused fallback)."""
    mesh = cb.mesh
    ops = cb.ops
    cut_vars = list(popt.get("cut_vars") or [])
    if len(cut_vars) < 3:
        return _fallback("need >= 3 cut vars (pre | stages... | post)")
    producer = {}
    for i, op in enumerate(ops):
        for n in op.output_arg_names:
            producer.setdefault(n, i)
    missing = [c for c in cut_vars if c not in producer]
    if missing:
        return _fallback(f"cut vars {missing} not produced")
    cut_vars.sort(key=lambda c: producer[c])
    bounds = [producer[c] + 1 for c in cut_vars]
    plan = PipelinePlan()
    plan.n_stages = len(cut_vars) - 1
    if mesh.shape.get("pp") != plan.n_stages:
        return _fallback(
            f"{plan.n_stages} interior sections vs pp axis size "
            f"{mesh.shape.get('pp')}")
    plan.n_micro = max(1, int(popt.get("num_microbatches", 1)))
    plan.c0, plan.c_last = cut_vars[0], cut_vars[-1]
    # activation contract: every cut var has the same shape (gpipe ring
    # buffers one activation shape through all stages)
    bvars = cb.program.global_block().vars
    cshapes = {tuple(bvars[c].shape) for c in cut_vars if c in bvars}
    if len(cshapes) != 1:
        return _fallback(
            f"cut activations have mismatched shapes {sorted(cshapes)}")
    plan.pre_ops = ops[:bounds[0]]
    sections = [ops[bounds[i]:bounds[i + 1]]
                for i in range(plan.n_stages)]
    rest = ops[bounds[-1]:]

    # ---- homogeneity + positional rename maps ---------------------------
    template = sections[0]
    if any(len(s) != len(template) for s in sections):
        return _fallback("sections differ in op count")
    for op in template:
        if op.type in _BATCH_MIXING:
            return _fallback(f"batch-mixing op '{op.type}' in a stage")
        if op.attrs.get("sub_block") is not None:
            return _fallback("control flow inside a stage")
        from ..ops.registry import OPS
        if OPS.has(op.type) and OPS.get(op.type).needs_rng:
            return _fallback(f"rng op '{op.type}' in a stage")
    maps: List[Dict[str, str]] = []  # template name -> stage-i name
    for sec in sections:
        m: Dict[str, str] = {}
        for top, sop in zip(template, sec):
            if _op_signature(top) != _op_signature(sop):
                return _fallback(
                    f"op mismatch: {top.type} vs {sop.type}")
            for tn, sn in zip(
                    list(top.input_arg_names) + list(top.output_arg_names),
                    list(sop.input_arg_names) + list(sop.output_arg_names)):
                if m.setdefault(tn, sn) != sn:
                    return _fallback(
                        f"inconsistent rename {tn} -> {m[tn]}/{sn}")
        maps.append(m)

    # externals of the template = read before written inside the section
    written: set = set()
    externals: List[str] = []
    for op in template:
        for n in op.input_arg_names:
            if n not in written and n not in externals:
                externals.append(n)
        written.update(op.output_arg_names)
    state = set(cb.mut_state) | set(cb.ro_state)
    all_written = set()
    for op in ops:
        all_written.update(op.output_arg_names)
    for n in externals:
        stage_names = [m[n] for m in maps]
        if n == plan.c0:
            continue  # the pipelined activation input
        if all(sn == n for sn in stage_names):
            if n in state and grad_var_name(n) in all_written:
                # a trainable param SHARED by every stage: its grad ops
                # live inside the interior span the vjp replaces, but
                # the vjp differentiates only stacked params + x0 — the
                # tied weight would silently get no gradient
                return _fallback(
                    f"stage-shared trainable param '{n}' (tied weights "
                    f"across stages can't ride the stacked vjp)")
            plan.closure_names.append(n)
            continue
        if not all(sn in state for sn in stage_names):
            return _fallback(
                f"stage-varying input '{n}' is not persistent state "
                f"({stage_names})")
        scope = cb._scope_ref()
        shapes = {tuple(scope.find_var(sn).get_tensor().array.shape)
                  for sn in stage_names}
        if len(shapes) != 1:
            return _fallback(
                f"stage-varying input '{n}' has mismatched shapes "
                f"across stages ({sorted(shapes)}) — params must stack")
        plan.param_template.append(n)
        plan.param_stage_names.append(stage_names)
    # the template's cut output (stage i writes cut_vars[i+1])
    out_name = None
    for tn, sn in maps[0].items():
        if sn == cut_vars[1] and tn in written:
            out_name = tn
            break
    if out_name is None or any(m.get(out_name) != cut_vars[i + 1]
                               for i, m in enumerate(maps)):
        return _fallback("stage output does not line up with cut vars")
    plan.template_out = out_name
    plan.template_ops = template

    # ---- split the remainder: post span / interior bwd span / tail ------
    interior_written = set()
    for sec in sections:
        for op in sec:
            interior_written.update(op.output_arg_names)
    # interior activations never materialize under the plan — a fetch of
    # one must take the fused path (c_last itself IS produced)
    hidden = (interior_written - {plan.c_last}) & set(cb.fetch_names)
    if hidden:
        return _fallback(
            f"fetch of interior activation(s) {sorted(hidden)} — the "
            f"pipelined schedule does not materialize them")
    grad_owned = set()
    for v in (interior_written - {plan.c_last}) | {plan.c0} | {
            n for names in plan.param_stage_names for n in names}:
        grad_owned.add(grad_var_name(v))

    def _writes_interior_grad(op):
        for n in op.output_arg_names:
            for g in grad_owned:
                if n == g or n.startswith(g + "@"):
                    return True
        return False

    idxs = [i for i, op in enumerate(rest) if _writes_interior_grad(op)]
    if not idxs:
        return _fallback("no interior gradient ops found in remainder")
    lo, hi = min(idxs), max(idxs)
    span = rest[lo:hi + 1]
    if any(not _writes_interior_grad(op) for op in span):
        return _fallback("interior gradient ops are not contiguous")
    plan.post_ops = rest[:lo]
    plan.tail_ops = rest[hi + 1:]
    return plan


def exec_plan(cb, plan: PipelinePlan, env: Dict[str, Any], lod_env, rng):
    """Execute one pipelined step into ``env`` (called from
    _CompiledBlock._step inside jit)."""
    from ..parallel.pipeline import gpipe

    cb._exec_ops(plan.pre_ops, env, lod_env, rng)
    x0 = env[plan.c0]
    B = x0.shape[0]
    if B % plan.n_micro:
        raise ValueError(
            f"batch {B} not divisible by num_microbatches={plan.n_micro}")
    stacked = [jnp.stack([env[n] for n in names])
               for names in plan.param_stage_names]
    closure = {n: env[n] for n in plan.closure_names}

    def stage_fn(params, x):
        e = dict(closure)
        for tn, v in zip(plan.param_template, params):
            e[tn] = v
        e[plan.c0] = x
        cb._exec_ops(plan.template_ops, e, dict(lod_env), rng)
        return e[plan.template_out]

    def interior(stacked_params, x0_):
        xs = x0_.reshape((plan.n_micro, B // plan.n_micro) + x0_.shape[1:])
        ys = gpipe(stage_fn, stacked_params, xs, mesh=cb.mesh)
        return ys.reshape(x0_.shape)

    y, vjp_fn = jax.vjp(interior, stacked, x0)
    env[plan.c_last] = y
    cb._exec_ops(plan.post_ops, env, lod_env, rng)
    gy_name = grad_var_name(plan.c_last)
    if gy_name not in env:
        raise KeyError(
            f"post span did not produce {gy_name} — cannot run the "
            f"reverse pipeline")
    d_stacked, d_x0 = vjp_fn(env[gy_name].astype(y.dtype))
    env[grad_var_name(plan.c0)] = d_x0
    for names, g in zip(plan.param_stage_names, d_stacked):
        for i, n in enumerate(names):
            env[grad_var_name(n)] = g[i]
    cb._exec_ops(plan.tail_ops, env, lod_env, rng)
