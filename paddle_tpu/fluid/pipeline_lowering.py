"""Lower a PipelineOptimizer-sectioned fluid program onto the compiled
GPipe schedule.

The reference executes sectioned programs through a thread/queue runtime
(reference: python/paddle/fluid/optimizer.py:3550 PipelineOptimizer,
paddle/fluid/framework/section_worker.cc:142, pipeline_trainer.cc:24).
The TPU inversion compiles the schedule instead: the interior sections
become ONE `parallel.pipeline` call (shard_map over the "pp" mesh axis,
lax.ppermute stage handoff) embedded in the executor's single jitted
step, and the interior's backward ops are replaced by the `jax.vjp` of
that call — the ppermute transposes run the reverse pipeline. Pre ops
(up to the first cut), post/loss/optimizer ops and every non-interior
gradient still execute on the normal traced path, so feeds, state
donation, fetches and the optimizer all work unchanged.

Two schedules, tried in order (`build_plan`):
  * homogeneous — sections share one op template; stage params STACK
    with a leading stage dim sharded over "pp" (`parallel.pipeline.gpipe`).
    Work- and memory-optimal.
  * heterogeneous — arbitrary per-stage bodies and activation shapes
    (`parallel.pipeline.gpipe_het`, lax.switch over the stage index on a
    flat max-size ring buffer — the compiled equivalent of the
    reference's SectionWorker running arbitrary sections,
    section_worker.cc:142). Params are replicated; per-device compute is
    still one stage per tick. Tied (stage-shared) trainable params ride
    this path too: each owning stage contributes a grad and they sum.

Common preconditions (anything else falls back to the fused path with a
warning — numerically identical, just not stage-parallel):
  * mesh has a "pp" axis whose size == number of interior sections
  * interior ops are batch-row-independent (no batch_norm/data_norm),
    rng-free (dropout inside a stage would draw per-stage masks the
    fused oracle can't mirror), and sub-block-free
  * no cross-stage reads of interior activations (skip connections
    across cut boundaries don't fit a 1-activation ring)
  * the microbatch count divides the feed batch
"""
from __future__ import annotations

import warnings
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from .backward import grad_var_name

# ops whose output for one batch row depends on other rows — microbatch
# splitting changes their semantics, so the interior may not contain them
_BATCH_MIXING = {"batch_norm", "sync_batch_norm", "data_norm"}


class PipelinePlan:
    def __init__(self):
        self.het = False            # heterogeneous schedule?
        self.pre_ops = []           # ops up to and incl. the c0 producer
        self.template_ops = []      # homog: section-1 ops (the stage body)
        self.post_ops = []          # post fwd + loss + post bwd
        self.tail_ops = []          # pre bwd + optimizer updates
        self.n_stages = 0
        self.n_micro = 1
        self.c0 = None              # activation entering the interior
        self.c_last = None          # activation leaving the interior
        self.template_out = None    # homog: template name of stage output
        self.closure_names = []     # homog: externals shared by every stage
        self.param_template = []    # homog: template name per stacked pos
        self.param_stage_names = []  # homog per position: [stage0..]
        # het fields
        self.sections = []          # het: per-stage op lists
        self.cut_vars = []          # het: sorted cut vars (len n_stages+1)
        self.sec_param_names = []   # het: per stage, differentiable externals
        self.sec_closure = []       # het: per stage, closure externals


def _op_signature(op):
    attrs = {k: v for k, v in op.attrs.items()
             if not k.startswith("_") and k != "op_role"}
    return (op.type, sorted(attrs.items(), key=lambda kv: kv[0]))


def _fallback(reason):
    warnings.warn(
        f"PipelineOptimizer program not lowerable onto the gpipe "
        f"schedule ({reason}); executing fused (numerically identical, "
        f"not stage-parallel)", stacklevel=3)
    return None


def _section_externals(sec):
    """Names a section reads before writing, in first-use order."""
    written: set = set()
    externals: List[str] = []
    for op in sec:
        for n in op.input_arg_names:
            if n not in written and n not in externals:
                externals.append(n)
        written.update(op.output_arg_names)
    return externals, written


def _batch_aligned(cb, name):
    """True when the block var's leading dim is the dynamic batch (-1):
    such a closure input would enter the per-MICROBATCH stage body at
    full-batch shape — broken semantics, so the planner must reject it."""
    v = cb.program.global_block().vars.get(name)
    return v is not None and len(v.shape) > 0 and v.shape[0] == -1


def _finish_plan(cb, plan, rest, interior_written, param_names_flat):
    """Shared tail of both planners: split the remainder around the
    interior-backward span the vjp replaces, and statically verify the
    replacement is sound. Fills plan.post_ops/tail_ops; returns None on
    success, a reason string on failure."""
    grad_owned = set()
    for v in (interior_written - {plan.c_last}) | {plan.c0} | set(
            param_names_flat):
        grad_owned.add(grad_var_name(v))

    def writes_interior_grad(op):
        for n in op.output_arg_names:
            for g in grad_owned:
                if n == g or n.startswith(g + "@"):
                    return True
        return False

    idxs = [i for i, op in enumerate(rest) if writes_interior_grad(op)]
    if not idxs:
        return "no interior gradient ops found in remainder"
    lo, hi = min(idxs), max(idxs)
    span = rest[lo:hi + 1]
    if any(not writes_interior_grad(op) for op in span):
        return "interior gradient ops are not contiguous"
    post, tail = rest[:lo], rest[hi + 1:]
    # a stage param also read by the pre/post FORWARD spans (e.g. an
    # embedding tied to the output head) would contribute gradient from
    # outside the interior — the vjp we substitute only sums the
    # interior contributions, so the grad would be silently wrong
    outside_reads = set()
    for op in list(plan.pre_ops) + list(post):
        outside_reads.update(op.input_arg_names)
    shared = sorted(set(param_names_flat) & outside_reads)
    if shared:
        return (f"stage param(s) {shared} also read by pre/post ops — "
                f"their out-of-interior grad contributions can't ride "
                f"the interior vjp")
    # the reverse pipeline needs the c_last cotangent from the post span
    gy = grad_var_name(plan.c_last)
    if not any(gy in op.output_arg_names for op in post):
        return (f"post span does not produce {gy} — cannot run the "
                f"reverse pipeline")
    # outputs of the replaced span may only be consumed downstream if we
    # recompute them ourselves; anything else read later would vanish
    recomputed = {grad_var_name(plan.c0)}
    recomputed.update(grad_var_name(n) for n in param_names_flat)
    dropped = set()
    for op in span:
        dropped.update(op.output_arg_names)
    later_reads = set(cb.fetch_names)
    for op in tail:
        later_reads.update(op.input_arg_names)
    leaked = sorted((dropped - recomputed) & later_reads)
    if leaked:
        return (f"replaced backward span outputs {leaked} are consumed "
                f"outside the interior")
    plan.post_ops, plan.tail_ops = post, tail
    return None


def _plan_homogeneous(cb, plan, sections, rest, all_written,
                      interior_written):
    """Fill the stacked-template fields of ``plan``; returns the plan or
    a reason string."""
    cut_vars = plan.cut_vars
    bvars = cb.program.global_block().vars
    cshapes = {tuple(bvars[c].shape) for c in cut_vars if c in bvars}
    if len(cshapes) != 1:
        return f"cut activations have mismatched shapes {sorted(cshapes)}"
    template = sections[0]
    if any(len(s) != len(template) for s in sections):
        return "sections differ in op count"
    maps: List[Dict[str, str]] = []  # template name -> stage-i name
    for sec in sections:
        m: Dict[str, str] = {}
        for top, sop in zip(template, sec):
            if _op_signature(top) != _op_signature(sop):
                return f"op mismatch: {top.type} vs {sop.type}"
            for tn, sn in zip(
                    list(top.input_arg_names) + list(top.output_arg_names),
                    list(sop.input_arg_names) + list(sop.output_arg_names)):
                if m.setdefault(tn, sn) != sn:
                    return f"inconsistent rename {tn} -> {m[tn]}/{sn}"
        maps.append(m)

    externals, written = _section_externals(template)
    state = set(cb.mut_state) | set(cb.ro_state)
    for n in externals:
        stage_names = [m[n] for m in maps]
        if n == plan.c0:
            continue  # the pipelined activation input
        if all(sn == n for sn in stage_names):
            if n in state and grad_var_name(n) in all_written:
                # a trainable param SHARED by every stage can't ride the
                # stacked vjp (the het path handles it instead)
                return (f"stage-shared trainable param '{n}' (tied "
                        f"weights across stages can't stack)")
            if _batch_aligned(cb, n):
                return (f"stage closure input '{n}' is batch-aligned — "
                        f"it cannot enter the per-microbatch stage body")
            plan.closure_names.append(n)
            continue
        if not all(sn in state for sn in stage_names):
            return (f"stage-varying input '{n}' is not persistent state "
                    f"({stage_names})")
        scope = cb._scope_ref()
        shapes = {tuple(scope.find_var(sn).get_tensor().array.shape)
                  for sn in stage_names}
        if len(shapes) != 1:
            return (f"stage-varying input '{n}' has mismatched shapes "
                    f"across stages ({sorted(shapes)}) — params must stack")
        plan.param_template.append(n)
        plan.param_stage_names.append(stage_names)
    # the template's cut output (stage i writes cut_vars[i+1])
    out_name = None
    for tn, sn in maps[0].items():
        if sn == cut_vars[1] and tn in written:
            out_name = tn
            break
    if out_name is None or any(m.get(out_name) != cut_vars[i + 1]
                               for i, m in enumerate(maps)):
        return "stage output does not line up with cut vars"
    plan.template_out = out_name
    plan.template_ops = template

    err = _finish_plan(cb, plan, rest, interior_written,
                       [n for names in plan.param_stage_names
                        for n in names])
    return plan if err is None else err


def _plan_het(cb, plan, sections, rest, all_written, interior_written):
    """Fill the heterogeneous fields of ``plan``; returns the plan or a
    reason string. Reference semantics: section_worker.cc:142 runs
    arbitrary per-device sections."""
    cut_vars = plan.cut_vars
    state = set(cb.mut_state) | set(cb.ro_state)
    bvars = cb.program.global_block().vars
    cdtypes = {str(bvars[c].dtype) for c in cut_vars if c in bvars}
    if len(cdtypes) > 1:
        return (f"cut activations have mismatched dtypes "
                f"{sorted(cdtypes)} — the ring buffer carries one dtype")
    sec_written = []
    for sec in sections:
        w = set()
        for op in sec:
            w.update(op.output_arg_names)
        sec_written.append(w)
    pre_written = set()
    for op in plan.pre_ops:
        pre_written.update(op.output_arg_names)
    preceding: set = set()  # union of vars written by sections 0..i-1
    for i, sec in enumerate(sections):
        if cut_vars[i + 1] not in sec_written[i]:
            return (f"section {i} does not produce its cut var "
                    f"'{cut_vars[i + 1]}'")
        externals, _ = _section_externals(sec)
        params, closure = [], []
        for n in externals:
            if n == cut_vars[i]:
                continue  # the ring activation input
            # a read of ANY preceding section's output is a cross-stage
            # read — including read-before-overwrite where this section
            # also writes n itself (n in sec_written[i] must NOT mask the
            # check: the closure snapshot {n: env[n]} would KeyError
            # inside the jitted step, since interior writes never land in
            # env)
            if n in preceding:
                return (f"section {i} reads '{n}' produced by a "
                        f"preceding section (cross-stage skip doesn't "
                        f"fit the 1-activation ring)")
            # n written only by this or a LATER section: the fused
            # oracle would read the pre-interior value — it must exist
            # outside the interior (pre ops or state), else the closure
            # snapshot has nothing to snapshot
            if n in interior_written and n not in pre_written \
                    and n not in state:
                return (f"section {i} reads '{n}' before it is written "
                        f"inside the interior, and no pre-section op or "
                        f"state provides it")
            if n in state and grad_var_name(n) in all_written:
                params.append(n)
            else:
                if grad_var_name(n) in all_written:
                    return (f"section {i} closure input '{n}' needs a "
                            f"gradient but is not persistent state")
                if _batch_aligned(cb, n):
                    return (f"section {i} closure input '{n}' is "
                            f"batch-aligned — it cannot enter the "
                            f"per-microbatch stage body")
                closure.append(n)
        plan.sec_param_names.append(params)
        plan.sec_closure.append(closure)
        preceding |= sec_written[i]
    plan.het = True
    plan.sections = sections
    err = _finish_plan(cb, plan, rest, interior_written,
                       [n for names in plan.sec_param_names
                        for n in names])
    return plan if err is None else err


def build_plan(cb, popt) -> Optional[PipelinePlan]:
    """cb: the _CompiledBlock being built. Returns a PipelinePlan or None
    (fused fallback)."""
    mesh = cb.mesh
    ops = cb.ops
    cut_vars = list(popt.get("cut_vars") or [])
    if len(cut_vars) < 3:
        return _fallback("need >= 3 cut vars (pre | stages... | post)")
    producer = {}
    for i, op in enumerate(ops):
        for n in op.output_arg_names:
            producer.setdefault(n, i)
    missing = [c for c in cut_vars if c not in producer]
    if missing:
        return _fallback(f"cut vars {missing} not produced")
    cut_vars.sort(key=lambda c: producer[c])
    bounds = [producer[c] + 1 for c in cut_vars]
    plan = PipelinePlan()
    plan.n_stages = len(cut_vars) - 1
    if mesh.shape.get("pp") != plan.n_stages:
        return _fallback(
            f"{plan.n_stages} interior sections vs pp axis size "
            f"{mesh.shape.get('pp')}")
    plan.n_micro = max(1, int(popt.get("num_microbatches", 1)))
    plan.c0, plan.c_last = cut_vars[0], cut_vars[-1]
    plan.cut_vars = cut_vars
    plan.pre_ops = ops[:bounds[0]]
    sections = [ops[bounds[i]:bounds[i + 1]]
                for i in range(plan.n_stages)]
    rest = ops[bounds[-1]:]

    # common per-op checks over EVERY section
    from ..ops.registry import OPS
    for sec in sections:
        for op in sec:
            if op.type in _BATCH_MIXING:
                return _fallback(f"batch-mixing op '{op.type}' in a stage")
            if op.attrs.get("sub_block") is not None:
                return _fallback("control flow inside a stage")
            if OPS.has(op.type) and OPS.get(op.type).needs_rng:
                return _fallback(f"rng op '{op.type}' in a stage")

    all_written = set()
    for op in ops:
        all_written.update(op.output_arg_names)
    # interior activations never materialize under either plan — a fetch
    # of one must take the fused path (c_last itself IS produced)
    interior_written = set()
    for sec in sections:
        for op in sec:
            interior_written.update(op.output_arg_names)
    hidden = (interior_written - {plan.c_last}) & set(cb.fetch_names)
    if hidden:
        return _fallback(
            f"fetch of interior activation(s) {sorted(hidden)} — the "
            f"pipelined schedule does not materialize them")

    homog = _plan_homogeneous(cb, plan, sections, rest, all_written,
                              interior_written)
    if isinstance(homog, PipelinePlan):
        return homog
    plan2 = PipelinePlan()
    plan2.n_stages, plan2.n_micro = plan.n_stages, plan.n_micro
    plan2.c0, plan2.c_last = plan.c0, plan.c_last
    plan2.cut_vars, plan2.pre_ops = plan.cut_vars, plan.pre_ops
    het = _plan_het(cb, plan2, sections, rest, all_written,
                    interior_written)
    if isinstance(het, PipelinePlan):
        return het
    return _fallback(f"homogeneous: {homog}; heterogeneous: {het}")


def exec_plan(cb, plan: PipelinePlan, env: Dict[str, Any], lod_env, rng):
    """Execute one pipelined step into ``env`` (called from
    _CompiledBlock._step inside jit)."""
    if plan.het:
        return _exec_het(cb, plan, env, lod_env, rng)
    from ..parallel.pipeline import gpipe

    cb._exec_ops(plan.pre_ops, env, lod_env, rng)
    x0 = env[plan.c0]
    B = x0.shape[0]
    if B % plan.n_micro:
        raise ValueError(
            f"batch {B} not divisible by num_microbatches={plan.n_micro}")
    stacked = [jnp.stack([env[n] for n in names])
               for names in plan.param_stage_names]
    closure = {n: env[n] for n in plan.closure_names}

    def stage_fn(params, x):
        e = dict(closure)
        for tn, v in zip(plan.param_template, params):
            e[tn] = v
        e[plan.c0] = x
        cb._exec_ops(plan.template_ops, e, dict(lod_env), rng)
        return e[plan.template_out]

    def interior(stacked_params, x0_):
        xs = x0_.reshape((plan.n_micro, B // plan.n_micro) + x0_.shape[1:])
        ys = gpipe(stage_fn, stacked_params, xs, mesh=cb.mesh)
        return ys.reshape(x0_.shape)

    y, vjp_fn = jax.vjp(interior, stacked, x0)
    env[plan.c_last] = y
    cb._exec_ops(plan.post_ops, env, lod_env, rng)
    gy_name = grad_var_name(plan.c_last)
    if gy_name not in env:
        raise KeyError(
            f"post span did not produce {gy_name} — cannot run the "
            f"reverse pipeline")
    d_stacked, d_x0 = vjp_fn(env[gy_name].astype(y.dtype))
    env[grad_var_name(plan.c0)] = d_x0
    for names, g in zip(plan.param_stage_names, d_stacked):
        for i, n in enumerate(names):
            env[grad_var_name(n)] = g[i]
    cb._exec_ops(plan.tail_ops, env, lod_env, rng)


def _exec_het(cb, plan: PipelinePlan, env: Dict[str, Any], lod_env, rng):
    """Heterogeneous schedule: per-stage bodies via gpipe_het."""
    from ..parallel.pipeline import gpipe_het

    cb._exec_ops(plan.pre_ops, env, lod_env, rng)
    x0 = env[plan.c0]
    B = x0.shape[0]
    if B % plan.n_micro:
        raise ValueError(
            f"batch {B} not divisible by num_microbatches={plan.n_micro}")
    params = [[env[n] for n in names] for names in plan.sec_param_names]
    closures = [{n: env[n] for n in cl} for cl in plan.sec_closure]

    def mk_stage(i):
        sec = plan.sections[i]
        in_name, out_name = plan.cut_vars[i], plan.cut_vars[i + 1]

        def f(p, x):
            e = dict(closures[i])
            for n, v in zip(plan.sec_param_names[i], p):
                e[n] = v
            e[in_name] = x
            cb._exec_ops(sec, e, dict(lod_env), rng)
            return e[out_name]
        return f

    stage_fns = [mk_stage(i) for i in range(plan.n_stages)]

    def interior(params_, x0_):
        xs = x0_.reshape((plan.n_micro, B // plan.n_micro) + x0_.shape[1:])
        ys = gpipe_het(stage_fns, params_, xs, mesh=cb.mesh)
        return ys.reshape((B,) + ys.shape[2:])

    y, vjp_fn = jax.vjp(interior, params, x0)
    env[plan.c_last] = y
    cb._exec_ops(plan.post_ops, env, lod_env, rng)
    gy_name = grad_var_name(plan.c_last)
    if gy_name not in env:
        raise KeyError(
            f"post span did not produce {gy_name} — cannot run the "
            f"reverse pipeline")
    d_params, d_x0 = vjp_fn(env[gy_name].astype(y.dtype))
    env[grad_var_name(plan.c0)] = d_x0
    # tied params may appear in several sections — their grads SUM
    acc: Dict[str, Any] = {}
    for names, gs in zip(plan.sec_param_names, d_params):
        for n, g in zip(names, gs):
            acc[n] = g if n not in acc else acc[n] + g
    for n, g in acc.items():
        env[grad_var_name(n)] = g
    cb._exec_ops(plan.tail_ops, env, lod_env, rng)
