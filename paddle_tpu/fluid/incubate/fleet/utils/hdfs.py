"""Filesystem clients for fleet checkpoints/data (reference:
incubate/fleet/utils/hdfs.py HDFSClient — shells out to ``hadoop fs`` the
same way framework/io/{fs.cc,shell.cc} do; plus a LocalFS with the same
interface so fleet code paths are testable without a cluster)."""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional, Tuple

__all__ = ["HDFSClient", "LocalFS"]


class FSClientBase:
    def ls(self, path) -> List[str]:
        raise NotImplementedError

    def is_exist(self, path) -> bool:
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdir(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst):
        raise NotImplementedError


class LocalFS(FSClientBase):
    """Same interface over the local filesystem (used by single-host tests
    and the default checkpoint path)."""

    def ls(self, path):
        return sorted(os.path.join(path, p) for p in os.listdir(path))

    def is_exist(self, path):
        return os.path.exists(path)

    def upload(self, local_path, fs_path):
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path, dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(fs_path) or ".", exist_ok=True)
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def mkdir(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst):
        shutil.move(src, dst)


class HDFSClient(FSClientBase):
    """``hadoop fs`` wrapper (reference hdfs.py HDFSClient — same shell
    strategy). Needs a hadoop binary on PATH or hadoop_home."""

    def __init__(self, hadoop_home: Optional[str] = None,
                 configs: Optional[dict] = None, retry_times: int = 3):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        self._configs = configs or {}
        self._retry = retry_times

    def _base_cmd(self) -> List[str]:
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        return cmd

    def _run(self, args: List[str], retry: bool = True) -> Tuple[int, str]:
        """retry=False for probes (``-test``, ``-ls``) where a nonzero exit
        is an expected answer, not a transient failure."""
        last = (1, "")
        for _ in range(self._retry if retry else 1):
            try:
                p = subprocess.run(self._base_cmd() + args,
                                   capture_output=True, text=True,
                                   timeout=300)
            except (FileNotFoundError, subprocess.TimeoutExpired) as e:
                raise RuntimeError(
                    f"hadoop binary unavailable or timed out: {e}") from e
            last = (p.returncode, p.stdout + p.stderr)
            if p.returncode == 0:
                return last
        return last

    def ls(self, path):
        code, out = self._run(["-ls", path], retry=False)
        if code != 0:
            return []
        return [line.split()[-1] for line in out.splitlines()
                if line and not line.startswith("Found")]

    def is_exist(self, path):
        code, _ = self._run(["-test", "-e", path], retry=False)
        return code == 0

    def upload(self, local_path, fs_path):
        code, out = self._run(["-put", "-f", local_path, fs_path])
        if code != 0:
            raise RuntimeError(f"hdfs upload failed: {out}")

    def download(self, fs_path, local_path):
        code, out = self._run(["-get", fs_path, local_path])
        if code != 0:
            raise RuntimeError(f"hdfs download failed: {out}")

    def mkdir(self, path):
        self._run(["-mkdir", "-p", path])

    def delete(self, path):
        self._run(["-rm", "-r", "-skipTrash", path])

    def mv(self, src, dst):
        code, out = self._run(["-mv", src, dst])
        if code != 0:
            raise RuntimeError(f"hdfs mv failed: {out}")
