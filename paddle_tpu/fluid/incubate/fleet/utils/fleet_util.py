"""FleetUtil — distributed training utilities (reference:
incubate/fleet/utils/fleet_util.py, 1,617 LoC: rank-0 logging, global AUC
and CTR metrics via GlooWrapper allreduce of local stat arrays, model
save/load over afs/hdfs).

TPU framing: inside a pod slice, metrics reductions belong IN the jitted
step (psum over the mesh). This host-side path covers the PS/dataset jobs
(reference's gloo ring): local stat arrays are summed across workers over
the ps_rpc plane (or trivially, single-host), then the metric closes the
same formula the metric ops use."""
from __future__ import annotations

import os
import sys
import time
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["FleetUtil"]


class FleetUtil:
    def __init__(self, mode: str = "pslib", fleet=None):
        self._fleet = fleet
        if fleet is None:
            if mode == "pslib":
                from ..parameter_server.pslib import fleet as f
            else:
                from ..collective import fleet as f
            self._fleet = f

    # ------------------------------------------------------------ logging
    def rank0_print(self, s: str):
        """reference fleet_util.py rank0_print."""
        try:
            if self._fleet.worker_index() != 0:
                return
        except Exception:
            pass
        print(s)
        sys.stdout.flush()

    rank0_info = rank0_print

    def rank0_error(self, s: str):
        try:
            if self._fleet.worker_index() != 0:
                return
        except Exception:
            pass
        print(s, file=sys.stderr)

    # ------------------------------------------------- global reductions
    def _all_reduce(self, arr: np.ndarray) -> np.ndarray:
        """Sum a host array across workers. Single-process jobs return the
        input; multi-host jobs ride the ps_rpc accumulate handler (the
        reference uses Gloo all_reduce — gloo_wrapper.h:146)."""
        arr = np.asarray(arr, np.float64)
        try:
            n = self._fleet.worker_num()
        except Exception:
            n = 1
        if n <= 1:
            return arr
        from ....ps_rpc import VarClient
        eps = self._fleet.server_endpoints()
        if not eps:
            return arr
        # sum on server 0's ReduceService (the pslib server registers
        # reduce_push/reduce_get handlers)
        cli = VarClient.of(eps[0])
        tid = self._fleet.worker_index()
        self._reduce_seq = getattr(self, "_reduce_seq", 0) + 1
        name = f"__fleet_util_reduce_{self._reduce_seq}__"
        cli.call("reduce_push", name=name, value=arr, trainer_id=tid)
        return np.asarray(cli.call("reduce_get", name=name, trainer_id=tid,
                                   world=n))

    # ------------------------------------------------------------ metrics
    def get_global_auc(self, scope=None, stat_pos: str = "_generated_var_2",
                       stat_neg: str = "_generated_var_3") -> float:
        """Close the AUC over the globally-summed threshold histograms
        (reference fleet_util.py get_global_auc; matches the auc op's
        StatPos/StatNeg layout — operators/metrics/auc_op)."""
        from ....executor import global_scope
        scope = scope or global_scope()
        pos = self._read(scope, stat_pos)
        neg = self._read(scope, stat_neg)
        pos = self._all_reduce(pos)
        neg = self._all_reduce(neg)
        from .....utils.metrics import auc_from_histograms
        return auc_from_histograms(pos, neg)

    def print_global_auc(self, scope=None, stat_pos="_generated_var_2",
                         stat_neg="_generated_var_3",
                         print_prefix: str = ""):
        auc = self.get_global_auc(scope, stat_pos, stat_neg)
        self.rank0_print(f"{print_prefix} global auc = {auc:.6f}")
        return auc

    def get_global_metrics(self, scope=None, stat_pos_name=None,
                           stat_neg_name=None, sqrerr_name=None,
                           abserr_name=None, prob_name=None, q_name=None,
                           pos_ins_num_name=None, total_ins_num_name=None):
        """reference get_global_metrics: returns [auc, bucket_error, mae,
        rmse, actual_ctr, predicted_ctr, copc, mean_q, pos_ins, total_ins]
        from globally-summed stat vars."""
        from ....executor import global_scope
        scope = scope or global_scope()

        def rd(name):
            return float(self._all_reduce(
                self._read(scope, name)).sum()) if name else 0.0

        total = rd(total_ins_num_name) or 1.0
        pos = rd(pos_ins_num_name)
        mae = rd(abserr_name) / total
        rmse = (rd(sqrerr_name) / total) ** 0.5
        predicted_ctr = rd(prob_name) / total
        actual_ctr = pos / total
        copc = actual_ctr / predicted_ctr if predicted_ctr > 0 else 0.0
        mean_q = rd(q_name) / pos if pos > 0 else 0.0
        auc = self.get_global_auc(scope, stat_pos_name, stat_neg_name) \
            if stat_pos_name and stat_neg_name else 0.0
        return [auc, 0.0, mae, rmse, actual_ctr, predicted_ctr, copc,
                mean_q, pos, total]

    @staticmethod
    def _read(scope, name: str) -> np.ndarray:
        v = scope.find_var(name)
        if v is None or not v.is_initialized():
            raise KeyError(f"stat var '{name}' not found in scope")
        return np.asarray(v.get_tensor().array, np.float64)

    # --------------------------------------------------------- save/load
    def save_paddle_model(self, executor, scope, program, model_path: str,
                          feeded_vars: Sequence[str] = (),
                          target_vars: Sequence = (), fs_client=None):
        """Save an inference model locally, then optionally upload
        (reference save_paddle_inference_model over hdfs)."""
        from .... import io as fluid_io
        from ....executor import scope_guard
        import tempfile
        local = model_path
        remote = None
        if fs_client is not None:
            remote = model_path
            local = tempfile.mkdtemp(prefix="fleet_model_")
        with scope_guard(scope):
            fluid_io.save_inference_model(local, list(feeded_vars),
                                          list(target_vars), executor,
                                          main_program=program)
        if fs_client is not None:
            fs_client.upload(local, remote)
        return local

    def load_paddle_model(self, executor, scope, model_path: str,
                          fs_client=None):
        from .... import io as fluid_io
        from ....executor import scope_guard
        import tempfile
        local = model_path
        if fs_client is not None:
            local = tempfile.mkdtemp(prefix="fleet_model_")
            fs_client.download(model_path, local)
        with scope_guard(scope):
            return fluid_io.load_inference_model(local, executor)

    # ------------------------------------------------------------- misc
    def print_on_rank(self, message: str, rank_id: int):
        try:
            if self._fleet.worker_index() != rank_id:
                return
        except Exception:
            pass
        print(message)

    def get_last_save_model(self, output_path: str, fs_client=None):
        """Newest saved epoch dir under output_path (reference
        get_last_save_model)."""
        fs = fs_client
        if fs is None:
            from .hdfs import LocalFS
            fs = LocalFS()
        if not fs.is_exist(output_path):
            return ""
        cands = [p for p in fs.ls(output_path)
                 if os.path.basename(p).startswith(("epoch_", "batch_"))]
        return max(cands, default="")
