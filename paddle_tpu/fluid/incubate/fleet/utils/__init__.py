"""fleet utils (reference: incubate/fleet/utils/)."""
from .fleet_util import FleetUtil
from .hdfs import HDFSClient, LocalFS

__all__ = ["FleetUtil", "HDFSClient", "LocalFS"]
