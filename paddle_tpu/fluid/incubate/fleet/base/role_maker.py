"""Role makers — cluster-membership discovery (reference:
python/paddle/fluid/incubate/fleet/base/role_maker.py — RoleMakerBase:33,
PaddleCloudRoleMaker:442 reading PADDLE_* envs, UserDefinedRoleMaker:946).

Same env contract as the reference launcher: PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS, PADDLE_CURRENT_ENDPOINT,
and for PS mode TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST."""
from __future__ import annotations

import os

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "UserDefinedCollectiveRoleMaker",
           "GeneralRoleMaker", "MPIRoleMaker", "MPISymetricRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role_is_generated = False
        self._role = None
        self._current_id = -1

    def is_worker(self):
        raise NotImplementedError

    def is_server(self):
        raise NotImplementedError

    def is_first_worker(self):
        return self.is_worker() and self.worker_index() == 0

    def worker_num(self):
        return len(self._worker_endpoints) or 1

    def server_num(self):
        return len(self._server_endpoints)

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def generate_role(self):
        raise NotImplementedError


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=False):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        if self._role_is_generated:
            return
        if self._is_collective:
            self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
            self._worker_endpoints = [
                e for e in os.getenv("PADDLE_TRAINER_ENDPOINTS",
                                     "").split(",") if e]
            self._training_role = "TRAINER"
            self._role = Role.WORKER
        else:
            role = os.getenv("TRAINING_ROLE", "TRAINER")
            self._worker_endpoints = [
                e for e in os.getenv("PADDLE_TRAINER_ENDPOINTS",
                                     "").split(",") if e]
            self._server_endpoints = [
                e for e in os.getenv("PADDLE_PSERVERS_IP_PORT_LIST",
                                     "").split(",") if e]
            if role == "TRAINER":
                self._role = Role.WORKER
                self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
            else:
                self._role = Role.SERVER
                cur = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
                port = os.getenv("PADDLE_PORT", "")
                ip = os.getenv("POD_IP", "")
                ep = cur or f"{ip}:{port}"
                self._current_id = self._server_endpoints.index(ep) \
                    if ep in self._server_endpoints else 0
        self._role_is_generated = True

    def is_worker(self):
        self.generate_role()
        return self._role == Role.WORKER

    def is_server(self):
        self.generate_role()
        return self._role == Role.SERVER

    def worker_num(self):
        self.generate_role()
        return max(len(self._worker_endpoints),
                   int(os.getenv("PADDLE_TRAINERS_NUM", "1")))

    def worker_index(self):
        self.generate_role()
        return self._current_id


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_endpoints = server_endpoints or []

    def generate_role(self):
        self._role_is_generated = True

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def worker_num(self):
        return self._worker_num


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._worker_endpoints = worker_endpoints or []

    def generate_role(self):
        self._role_is_generated = True

    def is_worker(self):
        return True

    def is_server(self):
        return False


class MPIRoleMaker(RoleMakerBase):
    """Name-compat shim for the reference's mpi4py-backed role maker
    (reference role_maker.py:151). Rank/size come from the launcher's
    env (PADDLE_TRAINER_ID / OMPI_COMM_WORLD_RANK); there is no MPI in
    the TPU runtime — collective messaging rides XLA collectives or the
    fleet TCP plane, so the MPI gather/barrier helpers raise with that
    pointer instead of silently doing nothing."""

    def __init__(self):
        super().__init__()
        self._rank = int(os.getenv("PADDLE_TRAINER_ID",
                                   os.getenv("OMPI_COMM_WORLD_RANK", "0")))
        self._size = int(os.getenv("PADDLE_TRAINERS_NUM",
                                   os.getenv("OMPI_COMM_WORLD_SIZE", "1")))
        self._role_is_generated = False

    def _get_rank(self):
        return self._rank

    def _get_size(self):
        return self._size

    def _no_mpi(self, what):
        raise RuntimeError(
            f"MPIRoleMaker.{what}: no MPI runtime on TPU — use the fleet "
            f"collective mode (XLA collectives over ICI/DCN) or the PS "
            f"TCP plane (fluid.ps_rpc) for cross-process messaging")

    def _all_gather(self, obj):
        self._no_mpi("_all_gather")

    def _worker_gather(self, obj):
        self._no_mpi("_worker_gather")

    def _barrier_all(self):
        self._no_mpi("_barrier_all")

    def _finalize(self):
        pass


class MPISymetricRoleMaker(MPIRoleMaker):
    """reference role_maker.py:226 — every node hosts one worker AND one
    pserver process: even ranks are servers (node_type 0), odd ranks
    workers (node_type 1), proc_per_node=2."""

    def __init__(self):
        super().__init__()
        self._proc_per_node = 2
        self._node_type = None

    def generate_role(self):
        if not self._role_is_generated:
            self._node_type = self._rank % self._proc_per_node
            self._role_is_generated = True

    def _check_role_generation(self):
        if not self._role_is_generated:
            raise NameError("generate_role() should be called first")
        return True

    def is_worker(self):
        return self._check_role_generation() and self._node_type == 1

    def is_server(self):
        return self._check_role_generation() and self._node_type == 0

    def is_first_worker(self):
        return self.is_worker() and self.worker_index() == 0

    def worker_num(self):
        self._check_role_generation()
        return self._size // self._proc_per_node

    def server_num(self):
        self._check_role_generation()
        return self._size // self._proc_per_node

    def worker_index(self):
        self._check_role_generation()
        return self._rank // self._proc_per_node

    def server_index(self):
        self._check_role_generation()
        return self._rank // self._proc_per_node


GeneralRoleMaker = PaddleCloudRoleMaker
