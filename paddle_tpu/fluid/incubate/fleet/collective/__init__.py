"""Fleet Collective mode — multi-device/multi-host data-parallel training
(reference: incubate/fleet/collective/__init__.py — Collective fleet:64,
CollectiveOptimizer:384, DistributedStrategy:36, _try_to_compile:516).

Inversion (SURVEY.md §2.3): the reference transpiles c_allreduce ops into
the program and builds NCCL rings keyed by ring_id. Here
``CollectiveOptimizer.minimize`` leaves the program alone; fleet's
``main_program`` becomes a CompiledProgram bound to a jax Mesh spanning all
devices of all hosts — batch sharded on "dp", params replicated; XLA emits
the ICI/DCN all-reduces. Multi-host rendezvous: jax.distributed.initialize
over the same PADDLE_TRAINER_* env contract. The knobs on
DistributedStrategy (nccl_comm_num, hierarchical allreduce, fuse_*) are
accepted for script parity; XLA already fuses and picks topologies."""
from __future__ import annotations

import os

from ..base.fleet_base import Fleet, DistributedOptimizer, Mode
from .....fluid import io as fluid_io
from .....fluid.compiler import CompiledProgram, BuildStrategy, \
    ExecutionStrategy
from .....fluid.framework import default_startup_program

__all__ = ["fleet", "Collective", "CollectiveOptimizer",
           "DistributedStrategy", "CollectiveOpBasedOptimizer"]


class DistributedStrategy:
    """reference: collective/__init__.py:36 + pybind BuildStrategy knobs."""

    def __init__(self):
        self.use_local_sgd = False
        self.use_dist_fc = False
        self.mode = "collective"
        self.collective_mode = "grad_allreduce"
        self.nccl_comm_num = 1
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scaling = 2 ** 15
        self.exec_strategy = ExecutionStrategy()
        self._build_strategy = BuildStrategy()

    @property
    def build_strategy(self):
        return self._build_strategy

    @build_strategy.setter
    def build_strategy(self, value):
        self._build_strategy = value


class Collective(Fleet):
    def __init__(self):
        super().__init__(Mode.COLLECTIVE)
        self._local_ip = 0
        self.startup_program = None
        self._origin_program = None
        self._transpiled_program = None
        self.main_program = None

    def init(self, role_maker=None):
        super().init(role_maker)
        self._init_distributed_runtime()

    def _init_distributed_runtime(self):
        """NCCL-id bootstrap equivalent: bring up jax.distributed across
        hosts using the PADDLE_* env contract (reference: gen_nccl_id over
        gRPC — operators/collective/c_gen_nccl_id_op.cc). Shared logic
        lives in parallel.env.init_distributed."""
        from paddle_tpu.parallel.env import init_distributed
        eps = self.worker_endpoints()
        init_distributed(coordinator_address=eps[0] if eps else None)

    def init_worker(self):
        pass

    def run_worker(self, main_programs=None, scopes=None):
        pass

    def init_server(self, model_dir=None):
        pass

    def run_server(self):
        pass

    def stop_worker(self):
        pass

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy)
        return self._optimizer

    def save_inference_model(self, executor, dirname, feeded_var_names=None,
                             target_vars=None, main_program=None,
                             export_for_deployment=True):
        fluid_io.save_inference_model(dirname, feeded_var_names,
                                      target_vars, executor,
                                      main_program or self._origin_program,
                                      None, None, export_for_deployment)

    def save_persistables(self, executor, dirname, main_program=None,
                          filename=None):
        fluid_io.save_persistables(executor, dirname,
                                   main_program or self._origin_program,
                                   filename)

    # ------------------------------------------------- epoch checkpoints
    # (reference collective/__init__.py:236 save_check_point / :287
    # load_check_point — HDFS-aware resumable epoch checkpoints tracked
    # by a TrainStatus)
    _CKPT_DIR = "__paddle_checkpoint__"

    def save_check_point(self, executor, path, train_status,
                         main_program=None, fs=None,
                         local_cache_path=".cache",
                         remain_all_checkpoint=False):
        """Save persistables + train status as checkpoint N under
        ``path/__paddle_checkpoint__/N`` via the fs client (LocalFS
        default; pass utils.HDFSClient for a cluster store)."""
        import json
        import os
        import shutil
        if fs is None:
            from ..utils.hdfs import LocalFS
            fs = LocalFS()
        root = os.path.join(path, self._CKPT_DIR)
        fs.mkdir(root)
        nums = self._checkpoint_nums(fs, root)
        n = (max(nums) + 1) if nums else 0
        local = os.path.join(local_cache_path, f"ckpt_{n}")
        # fresh staging dir: stale files from an earlier run must not ride
        # into (or nest under) the new checkpoint
        shutil.rmtree(local, ignore_errors=True)
        os.makedirs(local, exist_ok=True)
        self.save_persistables(executor, local, main_program)
        with open(os.path.join(local, "train_status.json"), "w") as f:
            json.dump({"epoch_no": train_status.epoch_no}, f)
        fs.upload(local, os.path.join(root, str(n)))
        if not remain_all_checkpoint:
            for old in nums:
                fs.delete(os.path.join(root, str(old)))
        return n

    def load_check_point(self, executor, path, trainer_id=0,
                         main_program=None, fs=None,
                         local_cache_path=".cache", ignore_empty=True):
        """Restore the newest checkpoint; returns a TrainStatus (epoch -1
        when nothing saved yet and ignore_empty)."""
        import json
        import os
        if fs is None:
            from ..utils.hdfs import LocalFS
            fs = LocalFS()
        root = os.path.join(path, self._CKPT_DIR)
        nums = self._checkpoint_nums(fs, root) if fs.is_exist(root) else []
        if not nums:
            if ignore_empty:
                return TrainStatus(-1)
            raise RuntimeError(f"no checkpoint under {root}")
        n = max(nums)
        local = os.path.join(local_cache_path, f"ckpt_load_{trainer_id}")
        # fresh download target: hadoop -get into an existing dir nests
        # instead of overwriting, silently restoring a stale checkpoint
        import shutil
        shutil.rmtree(local, ignore_errors=True)
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        fs.download(os.path.join(root, str(n)), local)
        fluid_io.load_persistables(executor, local,
                                   main_program or self._origin_program)
        with open(os.path.join(local, "train_status.json")) as f:
            return TrainStatus(json.load(f)["epoch_no"])

    @staticmethod
    def _checkpoint_nums(fs, root):
        import os
        if not fs.is_exist(root):
            return []
        nums = []
        for p in fs.ls(root):
            base = os.path.basename(p.rstrip("/"))
            if base.isdigit():
                nums.append(int(base))
        return nums


class TrainStatus:
    """Resumable-epoch tracker (reference collective/__init__.py:49)."""

    def __init__(self, epoch_no: int = -1):
        self.epoch_no = epoch_no

    def next(self) -> int:
        return self.epoch_no + 1

    def __eq__(self, other):
        return isinstance(other, TrainStatus) and \
            self.epoch_no == other.epoch_no

    def __ne__(self, other):
        return not self == other


fleet = Collective()


class CollectiveOpBasedOptimizer(DistributedOptimizer):
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)


class CollectiveOptimizer(DistributedOptimizer):
    """reference: collective/__init__.py:384."""

    def __init__(self, optimizer, strategy=None):
        if strategy is None:
            strategy = DistributedStrategy()
        super().__init__(optimizer, strategy)
        self._strategy = strategy
        self.print_config = False

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def _compile(self, main_program, loss_name):
        cp = CompiledProgram(main_program,
                             self._strategy.build_strategy)
        cp.with_data_parallel(loss_name=loss_name,
                              exec_strategy=self._strategy.exec_strategy)
        return cp

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        opt = self._optimizer
        if self._strategy.forward_recompute:
            from .....fluid.optimizer import RecomputeOptimizer
            opt = RecomputeOptimizer(opt)
            opt._set_checkpoints(self._strategy.recompute_checkpoints)
        if self._strategy.use_amp:
            from .....fluid.contrib import mixed_precision
            opt = mixed_precision.decorate(
                opt, init_loss_scaling=self._strategy.amp_loss_scaling)
        optimize_ops, param_grads = opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        main = loss.block.program
        fleet._origin_program = main
        fleet._transpiled_program = main
        fleet.main_program = self._compile(main, loss.name)
        fleet.startup_program = startup_program or \
            default_startup_program()
        return optimize_ops, param_grads
