"""Fleet parameter-server mode (reference: python/paddle/fluid/incubate/
fleet/parameter_server/distribute_transpiler/__init__.py — fleet singleton
wrapping DistributeTranspiler; init_worker/init_server/run_server lifecycle,
TranspilerOptimizer.minimize:...).

Usage parity with the reference:
    fleet.init(role_maker)
    optimizer = fleet.distributed_optimizer(fluid.optimizer.SGD(lr), config)
    optimizer.minimize(loss)
    if fleet.is_server(): fleet.init_server(); fleet.run_server()
    else: fleet.init_worker(); ...train...; fleet.stop_worker()
"""
from __future__ import annotations

import os

from paddle_tpu.fluid.incubate.fleet.base.fleet_base import (
    Fleet, Mode, DistributedOptimizer)
from paddle_tpu.fluid.transpiler.distribute_transpiler import (
    DistributeTranspiler, DistributeTranspilerConfig)


class DistributedTranspiler(Fleet):
    def __init__(self):
        super().__init__(Mode.TRANSPILER)
        self._transpiler = None
        self._main_program = None
        self._startup_program = None
        self._pserver_program = None
        self._pserver_startup = None

    # ------------------------------------------------------------ worker
    def init_worker(self):
        """Reference starts the async Communicator here (plus worker→server
        heartbeats — heart_beat_monitor.h); sync mode's variable traffic
        rides the send/recv ops, so only the beat thread starts."""
        from paddle_tpu.fluid.ps_rpc import WorkerHeartBeat
        self._heartbeat = WorkerHeartBeat(
            self.server_endpoints(), self.worker_index()).start()

    def run_worker(self, main_programs=None, scopes=None):
        pass

    def stop_worker(self):
        from paddle_tpu.fluid.ps_rpc import VarClient
        hb = getattr(self, "_heartbeat", None)
        if hb is not None:
            hb.stop()
        if self.worker_index() == 0:
            for ep in self.server_endpoints():
                try:
                    VarClient.of(ep).stop()
                except Exception:
                    pass
        VarClient.reset_pool()

    # ------------------------------------------------------------ server
    def init_server(self, model_dir=None):
        import paddle_tpu.fluid as fluid
        ep = self.server_endpoints()[self.server_index()]
        self._pserver_program = self._transpiler.get_pserver_program(ep)
        self._pserver_startup = self._transpiler.get_startup_program(
            ep, self._pserver_program)
        exe = fluid.Executor()
        exe.run(self._pserver_startup)
        self._server_exe = exe

    def run_server(self):
        if self._pserver_program is None:
            raise RuntimeError("init_server() must run before run_server()")
        self._server_exe.run(self._pserver_program)

    # --------------------------------------------------------- optimizer
    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = TranspilerOptimizer(optimizer, strategy, self)
        return self._optimizer

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from paddle_tpu.fluid import io
        io.save_inference_model(dirname, feeded_var_names, target_vars,
                                executor, main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from paddle_tpu.fluid import io
        io.save_persistables(executor, dirname, main_program)

    # ---------------------------------------------------------- internal
    def _transpile(self, config):
        import paddle_tpu.fluid as fluid
        if not isinstance(config, DistributeTranspilerConfig):
            config = DistributeTranspilerConfig()
        self._transpiler = DistributeTranspiler(config)
        self._transpiler.transpile(
            trainer_id=self.worker_index(),
            pservers=",".join(self.server_endpoints()),
            trainers=self.worker_num(),
            sync_mode=getattr(config, "sync_mode", True),
            program=fluid.default_main_program(),
            startup_program=fluid.default_startup_program())
        self._main_program = self._transpiler.get_trainer_program()
        self._startup_program = fluid.default_startup_program()

    @property
    def main_program(self):
        return self._main_program

    @property
    def startup_program(self):
        return self._startup_program


class TranspilerOptimizer(DistributedOptimizer):
    """reference: TranspilerOptimizer in the same file — wraps the user
    optimizer; minimize() = local minimize + program transpilation."""

    def __init__(self, optimizer, strategy=None, fleet_ref=None):
        super().__init__(optimizer, strategy)
        self._fleet = fleet_ref

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, scopes=None, startup_programs=None,
                 parameter_list=None, no_grad_set=None):
        res = self._optimizer.minimize(
            loss, startup_programs if not isinstance(startup_programs, list)
            else startup_programs[0], parameter_list, no_grad_set)
        self._fleet._transpile(self._strategy)
        return res


fleet = DistributedTranspiler()
