"""DownpourOptimizer program rewrite (reference:
pslib/optimizer_factory.py — DistributedAdam:68 finds the
distributed-lookup-table inputs/outputs/grads in the program and emits the
worker/server descriptors).

Rewrite performed here (TPU framing — dense math stays one jitted XLA step
on the chip; only the beyond-HBM sparse tables leave the graph):

  lookup_table(W, is_distributed=True)      →  pslib_pull_sparse(Ids)
  lookup_table_grad + W's optimizer-update  →  pslib_push_sparse(Ids, G)

Each rewritten embedding param becomes a DownpourSparseTable on the PS
side; everything else trains unchanged."""
from __future__ import annotations

from typing import Dict, List, Tuple

from .node import DownpourServer, DownpourWorker

__all__ = ["DistributedOptimizerImplBase", "DistributedAdam"]

_SPARSE_OPS = ("lookup_table", "lookup_table_v2")


class DistributedOptimizerImplBase:
    def __init__(self, optimizer):
        self._optimizer = optimizer

    def minimize(self, losses, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        raise NotImplementedError


class DistributedAdam(DistributedOptimizerImplBase):
    """reference optimizer_factory.py:68 — despite the name it wraps any
    inner optimizer; 'Adam' is the default server-side accessor."""

    def __init__(self, optimizer):
        super().__init__(optimizer)
        self.supported_embedding_types = list(_SPARSE_OPS)

    # ------------------------------------------------------------ scans
    def _find_sparse_params(self, program) -> Dict[str, List]:
        """{embedding param name: [its lookup ops]} for is_distributed
        lookups (reference :91 _find_distributed_lookup_table_inputs)."""
        found: Dict[str, List] = {}
        for op in program.global_block().ops:
            if op.type in _SPARSE_OPS and op.attrs.get("is_distributed"):
                w = op.input("W")[0]
                found.setdefault(w, []).append(op)
        return found

    # ---------------------------------------------------------- rewrite
    def minimize(self, losses, startup_program=None, parameter_list=None,
                 no_grad_set=None, strategy=None):
        from . import _runtime
        strategy = dict(strategy or {})
        if not isinstance(losses, (list, tuple)):
            losses = [losses]
        loss = losses[0]
        program = loss.block.program
        opt_ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)

        server = DownpourServer()
        worker = DownpourWorker()
        sparse = self._find_sparse_params(program)
        block = program.global_block()
        table_ids: Dict[str, int] = {}
        for tid, (w_name, lookups) in enumerate(sorted(sparse.items())):
            emb_dim = int(block.vars[w_name].shape[-1])
            server.add_sparse_table(
                tid, dict(strategy, sparse_embedx_dim=emb_dim))
            worker.add_sparse_table(
                tid,
                slot_key_vars=[lookups[0].input("Ids")[0]],
                slot_value_vars=[lookups[0].output("Out")[0]])
            table_ids[w_name] = tid
            spec = server.get_desc()["sparse_tables"][tid]
            _runtime.register_table_spec(
                tid, emb_dim, optimizer=spec["optimizer"],
                learning_rate=spec["learning_rate"],
                initial_range=spec["initial_range"])

        if table_ids:
            self._rewrite_program(program, table_ids)

        return opt_ops, params_grads, (server.get_desc(), worker.get_desc())

    def _rewrite_program(self, program, table_ids: Dict[str, int]):
        block = program.global_block()
        new_ops = []
        grad_of = {w + "@GRAD" for w in table_ids}
        for op in block.ops:
            if op.type in _SPARSE_OPS and op.attrs.get("is_distributed") \
                    and op.input("W")[0] in table_ids:
                w = op.input("W")[0]
                op.type = "pslib_pull_sparse"
                op.inputs = {"Ids": op.input("Ids")}
                op.attrs = {"TableId": table_ids[w],
                            "EmbeddingDim":
                                int(block.vars[w].shape[-1]),
                            "padding_idx": op.attrs.get("padding_idx", -1)}
                new_ops.append(op)
                continue
            if op.type in tuple(t + "_grad" for t in _SPARSE_OPS) \
                    and op.input("W") and op.input("W")[0] in table_ids:
                # grad wrt the table rows: push instead of materializing a
                # dense W@GRAD
                w = op.input("W")[0]
                pad = op.attrs.get("padding_idx", -1)
                op.type = "pslib_push_sparse"
                op.inputs = {"Ids": op.input("Ids"),
                             "Grads": op.input("Out@GRAD")}
                op.outputs = {}
                op.attrs = {"TableId": table_ids[w],
                            "EmbeddingDim":
                                int(block.vars[w].shape[-1]),
                            "padding_idx": pad}
                new_ops.append(op)
                continue
            # drop the dense optimizer update of a PS-held param
            if op.input_names and "Param" in op.inputs \
                    and op.inputs["Param"] \
                    and op.inputs["Param"][0] in table_ids:
                continue
            # drop ops consuming the (now absent) dense W@GRAD
            if any(n in grad_of for n in op.output_arg_names) \
                    or any(n in grad_of for n in op.input_arg_names):
                continue
            new_ops.append(op)
        block.ops = new_ops
        # the dense W@GRAD descs are orphans now — every op producing or
        # consuming them was dropped above; leaving them would ship dead
        # var descs (analysis.py dead-var rule)
        used = set()
        for op in new_ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
        for g in grad_of - used:
            block.vars.pop(g, None)
        program._version += 1
