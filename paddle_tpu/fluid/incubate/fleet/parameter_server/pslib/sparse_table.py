"""Downpour sparse tables — host-RAM id→row embedding store with built-in
optimizers (TPU-native replacement for the reference's external Baidu PSLib
C++ server the pslib fleet mode wraps: fleet_wrapper.h:86-190 pull/push,
node.py DownpourServer table descriptors).

Design: TPU HBM holds the dense model; beyond-HBM sparse embeddings live in
host RAM sharded by id across trainer hosts (id % shard_num). Rows are
created on first touch (lazy init), updated by the table's accessor rule
(sgd / adagrad / adam — the reference's DownpourSparseTable accessors), and
can be shrunk by last-seen time, saved/loaded, and served over the ps_rpc
plane for multi-host."""
from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["DownpourSparseTable", "DownpourDenseTable", "TableRegistry"]


class DownpourSparseTable:
    """One sparse table (reference: DownpourServer.add_sparse_table —
    pslib/node.py:55)."""

    def __init__(self, table_id: int, emb_dim: int, optimizer: str = "sgd",
                 learning_rate: float = 0.05, initial_range: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, seed: int = 0):
        self.table_id = table_id
        self.emb_dim = int(emb_dim)
        self.optimizer = optimizer
        self.lr = float(learning_rate)
        self.initial_range = float(initial_range)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self._rows: Dict[int, np.ndarray] = {}
        self._moments: Dict[int, np.ndarray] = {}
        self._moments2: Dict[int, np.ndarray] = {}
        self._step: Dict[int, int] = {}
        self._last_seen: Dict[int, float] = {}
        self._rng = np.random.RandomState(seed + table_id)
        self._lock = threading.RLock()

    # ----------------------------------------------------------- pull/push
    def _row(self, fid: int) -> np.ndarray:
        row = self._rows.get(fid)
        if row is None:
            row = self._rng.uniform(-self.initial_range, self.initial_range,
                                    self.emb_dim).astype(np.float32)
            self._rows[fid] = row
        self._last_seen[fid] = time.time()
        return row

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        with self._lock:
            return np.stack([self._row(int(i)) for i in ids]) \
                if len(ids) else np.zeros((0, self.emb_dim), np.float32)

    def push(self, ids: Sequence[int], grads: np.ndarray):
        """Apply grads row-wise under the table's accessor rule. Duplicate
        ids accumulate (reference sparse push semantics)."""
        grads = np.asarray(grads, np.float32).reshape(-1, self.emb_dim)
        with self._lock:
            agg: Dict[int, np.ndarray] = {}
            for i, g in zip(ids, grads):
                i = int(i)
                if i in agg:
                    agg[i] = agg[i] + g
                else:
                    agg[i] = g.copy()
            for i, g in agg.items():
                row = self._row(i)
                if self.optimizer == "sgd" or self.optimizer == "naive":
                    row -= self.lr * g
                elif self.optimizer == "adagrad":
                    m = self._moments.setdefault(
                        i, np.zeros(self.emb_dim, np.float32))
                    m += g * g
                    row -= self.lr * g / (np.sqrt(m) + self.epsilon)
                elif self.optimizer == "adam":
                    m = self._moments.setdefault(
                        i, np.zeros(self.emb_dim, np.float32))
                    v = self._moments2.setdefault(
                        i, np.zeros(self.emb_dim, np.float32))
                    t = self._step.get(i, 0) + 1
                    self._step[i] = t
                    m[:] = self.beta1 * m + (1 - self.beta1) * g
                    v[:] = self.beta2 * v + (1 - self.beta2) * g * g
                    mhat = m / (1 - self.beta1 ** t)
                    vhat = v / (1 - self.beta2 ** t)
                    row -= self.lr * mhat / (np.sqrt(vhat) + self.epsilon)
                else:
                    raise ValueError(f"unknown accessor {self.optimizer}")

    # ----------------------------------------------------------- lifecycle
    def shrink(self, max_idle_seconds: Optional[float] = None,
               keep_ids: Optional[set] = None) -> int:
        """Drop rows idle longer than the threshold (reference
        shrink_sparse_table)."""
        with self._lock:
            now = time.time()
            drop = [i for i, seen in self._last_seen.items()
                    if (max_idle_seconds is not None
                        and now - seen > max_idle_seconds)
                    and (keep_ids is None or i not in keep_ids)]
            for i in drop:
                self._rows.pop(i, None)
                self._moments.pop(i, None)
                self._moments2.pop(i, None)
                self._step.pop(i, None)
                self._last_seen.pop(i, None)
            return len(drop)

    def clear(self):
        with self._lock:
            self._rows.clear()
            self._moments.clear()
            self._moments2.clear()
            self._step.clear()
            self._last_seen.clear()

    def stat(self) -> Dict[str, float]:
        with self._lock:
            mem = sum(r.nbytes for r in self._rows.values())
            return {"row_count": len(self._rows), "mem_bytes": mem,
                    "emb_dim": self.emb_dim}

    def save(self, path: str):
        with self._lock, open(path, "wb") as f:
            pickle.dump({"emb_dim": self.emb_dim, "rows": self._rows,
                         "moments": self._moments,
                         "moments2": self._moments2,
                         "step": self._step}, f)

    def load(self, path: str):
        with open(path, "rb") as f:
            data = pickle.load(f)
        with self._lock:
            if data["emb_dim"] != self.emb_dim:
                raise ValueError(
                    f"table {self.table_id}: dim {data['emb_dim']} != "
                    f"{self.emb_dim}")
            self._rows = data["rows"]
            self._moments = data.get("moments", {})
            self._moments2 = data.get("moments2", {})
            self._step = data.get("step", {})
            now = time.time()
            self._last_seen = {i: now for i in self._rows}


class DownpourDenseTable:
    """Dense param table for PS-held dense weights (reference
    add_dense_table)."""

    def __init__(self, table_id: int, shapes: Dict[str, tuple],
                 learning_rate: float = 0.05):
        self.table_id = table_id
        self.lr = learning_rate
        self._params = {n: np.zeros(s, np.float32)
                        for n, s in shapes.items()}
        self._lock = threading.RLock()

    def pull(self):
        with self._lock:
            return {n: p.copy() for n, p in self._params.items()}

    def push(self, grads: Dict[str, np.ndarray]):
        with self._lock:
            for n, g in grads.items():
                self._params[n] -= self.lr * np.asarray(g, np.float32)

    def set(self, values: Dict[str, np.ndarray]):
        with self._lock:
            for n, v in values.items():
                self._params[n] = np.asarray(v, np.float32).copy()


class TableRegistry:
    """Process-local table store, the 'server' of the single-host pslib
    deployment; multi-host shards it behind ps_rpc.VarServer handlers."""

    def __init__(self):
        self.sparse: Dict[int, DownpourSparseTable] = {}
        self.dense: Dict[int, DownpourDenseTable] = {}

    def add_sparse(self, table: DownpourSparseTable):
        self.sparse[table.table_id] = table
        return table

    def add_dense(self, table: DownpourDenseTable):
        self.dense[table.table_id] = table
        return table

    def save_model(self, dirname: str):
        os.makedirs(dirname, exist_ok=True)
        for tid, t in self.sparse.items():
            t.save(os.path.join(dirname, f"sparse_table_{tid}.pkl"))
        for tid, t in self.dense.items():
            with open(os.path.join(dirname, f"dense_table_{tid}.pkl"),
                      "wb") as f:
                pickle.dump(t.pull(), f)

    def load_model(self, dirname: str):
        for tid, t in self.sparse.items():
            p = os.path.join(dirname, f"sparse_table_{tid}.pkl")
            if os.path.exists(p):
                t.load(p)
        for tid, t in self.dense.items():
            p = os.path.join(dirname, f"dense_table_{tid}.pkl")
            if os.path.exists(p):
                with open(p, "rb") as f:
                    t.set(pickle.load(f))
