"""fleet pslib mode — massive-sparse parameter server (reference:
incubate/fleet/parameter_server/pslib/__init__.py — PSLib:28 wrapping the
external Baidu PSLib downpour server via FleetWrapper, fleet_wrapper.h:86).

TPU-native replacement: the downpour tables are the in-repo host-RAM
sparse tables (sparse_table.py) sharded by feature id across pserver
processes and served over the ps_rpc TCP plane; the dense model never
leaves the chip. Same fleet API surface: init/init_worker/init_server/
run_server, distributed_optimizer → DownpourOptimizer, table save/load/
shrink/clear/stat."""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from ...base.fleet_base import Fleet, DistributedOptimizer, Mode
from .node import DownpourServer, DownpourWorker
from .sparse_table import (DownpourSparseTable, DownpourDenseTable,
                           TableRegistry)
from .optimizer_factory import DistributedAdam

__all__ = ["PSLib", "fleet", "DownpourOptimizer", "DownpourServer",
           "DownpourWorker", "DownpourSparseTable", "TableRegistry"]


class _PslibRuntime:
    """Routes table ops: local registry (single host / server process) or
    id-sharded RPC to pserver endpoints (worker in a multi-host job)."""

    def __init__(self):
        self.registry = TableRegistry()
        self.specs: Dict[int, dict] = {}
        self.endpoints: List[str] = []
        self._remote = False

    def register_table_spec(self, tid: int, emb_dim: int,
                            optimizer: str = "sgd",
                            learning_rate: float = 0.05,
                            initial_range: float = 0.01):
        self.specs[tid] = {"emb_dim": emb_dim, "optimizer": optimizer,
                           "learning_rate": learning_rate,
                           "initial_range": initial_range}
        if tid not in self.registry.sparse:
            self.registry.add_sparse(DownpourSparseTable(
                tid, emb_dim, optimizer, learning_rate,
                initial_range=initial_range))

    def connect(self, endpoints: List[str]):
        self.endpoints = list(endpoints)
        self._remote = bool(endpoints)

    def disconnect(self):
        self._remote = False
        self.endpoints = []

    # ------------------------------------------------------ pull / push
    def pull(self, tid: int, ids: np.ndarray) -> np.ndarray:
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        if not self._remote:
            return self.registry.sparse[tid].pull(flat)
        from .....ps_rpc import VarClient
        n = len(self.endpoints)
        shard = flat % n
        dim = self.specs[tid]["emb_dim"]
        out = np.zeros((flat.size, dim), np.float32)
        for s, ep in enumerate(self.endpoints):
            mask = shard == s
            if not mask.any():
                continue
            rows = VarClient.of(ep).call("pslib_pull", tid=tid,
                                         ids=flat[mask].tolist())
            out[mask] = np.asarray(rows, np.float32)
        return out

    def push(self, tid: int, ids: np.ndarray, grads: np.ndarray):
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        dim = self.specs[tid]["emb_dim"]
        grads = np.asarray(grads, np.float32).reshape(-1, dim)
        if not self._remote:
            self.registry.sparse[tid].push(flat, grads)
            return
        from .....ps_rpc import VarClient
        n = len(self.endpoints)
        shard = flat % n
        for s, ep in enumerate(self.endpoints):
            mask = shard == s
            if not mask.any():
                continue
            VarClient.of(ep).call("pslib_push", tid=tid,
                                  ids=flat[mask].tolist(),
                                  grads=grads[mask])


_runtime = _PslibRuntime()


class DownpourOptimizer(DistributedOptimizer):
    """reference __init__.py DownpourOptimizer — delegates to the
    DistributedAdam factory, stores worker/server descriptors on fleet."""

    def __init__(self, optimizer, strategy=None, fleet_ref=None):
        super().__init__(optimizer, strategy or {})
        self._impl = DistributedAdam(optimizer)
        self._fleet_ref = fleet_ref

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, losses, scopes=None, startup_programs=None,
                 parameter_list=None, no_grad_set=None):
        opt_ops, params_grads, descs = self._impl.minimize(
            losses, startup_programs, parameter_list, no_grad_set,
            strategy=self._strategy)
        owner = self._fleet_ref if self._fleet_ref is not None else fleet
        owner._server_desc, owner._worker_desc = descs
        if owner is not fleet:  # keep the module singleton in sync too
            fleet._server_desc, fleet._worker_desc = descs
        return opt_ops, params_grads


class PSLib(Fleet):
    def __init__(self):
        super().__init__(Mode.PSLIB)
        self._server_desc = None
        self._worker_desc = None
        self._server = None
        self._main_programs = []

    # ------------------------------------------------------------- roles
    def init_worker(self):
        """Connect to the pserver shard ring (reference :57 — starts the
        PSLib client + barriers)."""
        eps = self._role_maker.get_pserver_endpoints() or []
        if len(eps) > 0 and self._role_maker.server_num() > 0:
            _runtime.connect(eps)

    def run_worker(self, main_programs=None, scopes=None):
        self._main_programs = main_programs or []

    def init_server(self, model_dir: Optional[str] = None, **kwargs):
        """Materialize tables from the descriptors; optionally warm-start
        (reference :134)."""
        desc = self._server_desc or {"sparse_tables": {}}
        for tid, spec in desc["sparse_tables"].items():
            _runtime.register_table_spec(
                tid, spec["emb_dim"], spec["optimizer"],
                spec["learning_rate"],
                spec.get("initial_range", 0.01))
        if model_dir:
            _runtime.registry.load_model(model_dir)

    def run_server(self):
        """Serve this shard's tables over ps_rpc (reference :156)."""
        from .....ps_rpc import VarServer, ReduceService
        idx = self._role_maker.server_index()
        ep = self._role_maker.get_pserver_endpoints()[idx]
        reg = _runtime.registry

        def _pull(tid, ids):
            return reg.sparse[tid].pull(ids)

        def _push(tid, ids, grads):
            reg.sparse[tid].push(ids, np.asarray(grads))
            return True

        def _stat(tid):
            return reg.sparse[tid].stat()

        def _shrink(tid, max_idle_seconds):
            return reg.sparse[tid].shrink(max_idle_seconds)

        def _save(dirname):
            reg.save_model(dirname)
            return True

        port = ep.rsplit(":", 1)[1]
        handlers = {
            "pslib_pull": _pull, "pslib_push": _push,
            "pslib_stat": _stat, "pslib_shrink": _shrink,
            "pslib_save": _save}
        handlers.update(ReduceService().handlers())  # FleetUtil reductions
        self._server = VarServer(f"0.0.0.0:{port}", handlers).start()
        return self._server

    def stop_worker(self):
        _runtime.disconnect()

    def stop_server(self):
        if self._server is not None:
            self._server.shutdown()
            self._server = None

    # --------------------------------------------------------- optimizer
    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = DownpourOptimizer(optimizer, strategy,
                                            fleet_ref=self)
        return self._optimizer

    # ------------------------------------------------------- save / load
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from ..... import io as fluid_io
        return fluid_io.save_inference_model(dirname, feeded_var_names,
                                             target_vars, executor,
                                             main_program)

    def save_persistables(self, executor, dirname, main_program=None,
                          **kwargs):
        from ..... import io as fluid_io
        fluid_io.save_persistables(executor, dirname, main_program)
        self.save_model(dirname)

    def save_model(self, model_dir=None, **kwargs):
        """Snapshot the sparse tables (reference :617)."""
        _runtime.registry.save_model(model_dir)

    def load_model(self, model_dir=None, **kwargs):
        _runtime.registry.load_model(model_dir)

    def save_cache_model(self, executor, dirname, main_program=None,
                         cache_threshold: int = 0, **kwargs):
        """Export only hot rows for the serving cache (reference :301 —
        PSLib's cache table). Here: rows touched most recently first,
        keeping ``cache_threshold`` rows per table (0 = all)."""
        os.makedirs(dirname, exist_ok=True)
        import pickle
        for tid, t in _runtime.registry.sparse.items():
            with t._lock:
                items = sorted(t._last_seen.items(), key=lambda kv: -kv[1])
                if cache_threshold:
                    items = items[:cache_threshold]
                rows = {i: t._rows[i] for i, _ in items if i in t._rows}
            with open(os.path.join(dirname, f"cache_table_{tid}.pkl"),
                      "wb") as f:
                pickle.dump({"emb_dim": t.emb_dim, "rows": rows}, f)
        return sum(len(t._rows) for t in _runtime.registry.sparse.values())

    # ----------------------------------------------------- table control
    def print_table_stat(self, table_id):
        st = _runtime.registry.sparse[table_id].stat()
        print(f"table {table_id}: rows={st['row_count']} "
              f"mem={st['mem_bytes']}B dim={st['emb_dim']}")
        return st

    def shrink_sparse_table(self, max_idle_seconds: float = 0.0):
        return {tid: t.shrink(max_idle_seconds)
                for tid, t in _runtime.registry.sparse.items()}

    def shrink_dense_table(self, decay, emb_dim=11, scope=None,
                           table_id=None):
        for tid, t in _runtime.registry.dense.items():
            if table_id is not None and tid != table_id:
                continue
            with t._lock:
                for n in t._params:
                    t._params[n] *= decay

    def clear_one_table(self, table_id):
        _runtime.registry.sparse[table_id].clear()

    def clear_model(self):
        for t in _runtime.registry.sparse.values():
            t.clear()


fleet = PSLib()
