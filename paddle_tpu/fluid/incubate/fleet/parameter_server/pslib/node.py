"""Downpour server/worker descriptors (reference: pslib/node.py —
DownpourServer:38 add_sparse_table/add_dense_table, DownpourWorker:~).

The reference emits protobuf ps.proto descriptors consumed by the external
Baidu PSLib binary; here the descriptors are plain dicts that configure the
in-repo host-RAM table service (sparse_table.py) — same knobs (table id,
accessor class, emb dim, lr), TPU-native backend."""
from __future__ import annotations

from typing import Dict

__all__ = ["Server", "Worker", "DownpourServer", "DownpourWorker"]

_ACCESSOR_TO_OPT = {
    "DownpourSparseValueAccessor": "sgd",
    "DownpourCtrAccessor": "adagrad",
    "DownpourCtrDoubleAccessor": "adagrad",
    "DownpourUnitAccessor": "adam",
    "DownpourDoubleUnitAccessor": "adam",
}


class Server:
    def __init__(self):
        self._desc: Dict = {"sparse_tables": {}, "dense_tables": {},
                            "service": {"server_class": "TpuPsServer",
                                        "client_class": "TpuPsClient"}}

    def get_desc(self):
        return self._desc


class Worker:
    def __init__(self):
        self._desc: Dict = {"sparse_tables": {}, "dense_tables": {}}

    def get_desc(self):
        return self._desc


class DownpourServer(Server):
    """reference node.py:38 — accumulates table descriptors."""

    def add_sparse_table(self, table_id: int, strategy: Dict = None,
                         emb_dim: int = 8, learning_rate: float = 0.05):
        strategy = dict(strategy or {})
        accessor = strategy.get("sparse_accessor_class",
                                "DownpourSparseValueAccessor")
        if accessor not in _ACCESSOR_TO_OPT:
            raise ValueError(
                f"unsupported accessor {accessor}; one of "
                f"{sorted(_ACCESSOR_TO_OPT)}")
        self._desc["sparse_tables"][int(table_id)] = {
            "table_id": int(table_id),
            "emb_dim": int(strategy.get("sparse_embedx_dim", emb_dim)),
            "optimizer": _ACCESSOR_TO_OPT[accessor],
            "accessor_class": accessor,
            "learning_rate": float(
                strategy.get("sparse_learning_rate", learning_rate)),
            "initial_range": float(
                strategy.get("sparse_initial_range", 1e-4)),
        }

    def add_dense_table(self, table_id: int, param_shapes: Dict[str, tuple],
                        learning_rate: float = 0.05, strategy: Dict = None):
        strategy = dict(strategy or {})
        self._desc["dense_tables"][int(table_id)] = {
            "table_id": int(table_id),
            "param_shapes": {k: tuple(v) for k, v in param_shapes.items()},
            "learning_rate": float(
                strategy.get("dense_learning_rate", learning_rate)),
        }


class DownpourWorker(Worker):
    """reference node.py DownpourWorker — mirrors the tables the worker
    pulls/pushes."""

    def __init__(self, window: int = 1):
        super().__init__()
        self.window = window

    def add_sparse_table(self, table_id: int, slot_key_vars=None,
                         slot_value_vars=None):
        self._desc["sparse_tables"][int(table_id)] = {
            "table_id": int(table_id),
            "slot_key": [getattr(v, "name", v) for v in slot_key_vars or []],
            "slot_value": [getattr(v, "name", v)
                           for v in slot_value_vars or []],
        }

    def add_dense_table(self, table_id: int, learning_rate: float = 0.05,
                        param_vars=None, grad_vars=None):
        self._desc["dense_tables"][int(table_id)] = {
            "table_id": int(table_id),
            "params": [getattr(v, "name", v) for v in param_vars or []],
            "grads": [getattr(v, "name", v) for v in grad_vars or []],
        }
