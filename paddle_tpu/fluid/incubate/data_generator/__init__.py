"""User-side data generator protocol for Dataset ingestion (reference:
python/paddle/fluid/incubate/data_generator/__init__.py —
DataGenerator:20, MultiSlotDataGenerator; emits the slot text format the
native feed engine parses, paddle_tpu/native/datafeed.cpp)."""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # user overrides -----------------------------------------------------
    def generate_sample(self, line):
        """Returns a generator of [(slot_name, [values]), ...] per line."""
        raise NotImplementedError(
            "implement generate_sample(self, line) in your subclass")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    # runtime ------------------------------------------------------------
    def _gen_str(self, line):
        raise NotImplementedError

    def run_from_stdin(self):
        self._run(sys.stdin)

    def run_from_memory(self):
        self._run([None])

    def _run(self, lines):
        # accumulate batch_size_ samples, route each full batch through
        # generate_batch (user hook for per-batch pad/shuffle/merge), then
        # serialize — the reference DataGenerator contract
        batch = []
        for line in lines:
            for sample in self.generate_sample(line)():
                batch.append(sample)
                if len(batch) >= self.batch_size_:
                    self._flush(batch)
                    batch = []
        if batch:
            self._flush(batch)

    def _flush(self, samples):
        for sample in self.generate_batch(samples)():
            sys.stdout.write(self._gen_str(sample))


class MultiSlotDataGenerator(DataGenerator):
    """Emits `<n> v1 .. vn` per slot, space-joined (the MultiSlotDataFeed
    wire grammar — reference data_feed.cc CheckFile)."""

    def _gen_str(self, sample):
        parts = []
        for _name, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    pass
