"""Op frequency statistics (reference: contrib/op_frequence.py
op_freq_statistic:23 — counts op types and adjacent-pair frequencies over a
program; the pair counts were used to pick fusion candidates)."""
from __future__ import annotations

from collections import OrderedDict

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Return (uni_op_freq, adj_2_op_freq) ordered by count desc."""
    uni = {}
    adj = {}
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type] = uni.get(op.type, 0) + 1
            if prev is not None:
                key = f"{prev}->{op.type}"
                adj[key] = adj.get(key, 0) + 1
            prev = op.type
    uni_sorted = OrderedDict(sorted(uni.items(), key=lambda kv: -kv[1]))
    adj_sorted = OrderedDict(sorted(adj.items(), key=lambda kv: -kv[1]))
    return uni_sorted, adj_sorted
