"""contrib.utils (reference: contrib/utils/ — hdfs_utils re-exports the
HDFS client; lookup_table_utils converts distributed-lookup programs for
increment/inference loading)."""
from .hdfs_utils import HDFSClient, multi_download, multi_upload
from .lookup_table_utils import (convert_dist_to_sparse_program,
                                 get_inference_model)

__all__ = ["HDFSClient", "multi_download", "multi_upload",
           "convert_dist_to_sparse_program", "get_inference_model"]
