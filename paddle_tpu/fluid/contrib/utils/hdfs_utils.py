"""HDFS helpers (reference: contrib/utils/hdfs_utils.py — HDFSClient +
multi_download/multi_upload thread pools over hadoop fs)."""
from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import List

from ...incubate.fleet.utils.hdfs import HDFSClient

__all__ = ["HDFSClient", "multi_download", "multi_upload"]


def multi_download(client: HDFSClient, hdfs_path: str, local_path: str,
                   trainer_id: int = 0, trainers: int = 1,
                   multi_processes: int = 5) -> List[str]:
    """Download this trainer's shard of the files under hdfs_path
    (round-robin by index — reference multi_download)."""
    files = client.ls(hdfs_path)
    mine = [f for i, f in enumerate(sorted(files))
            if i % trainers == trainer_id]
    os.makedirs(local_path, exist_ok=True)

    def pull(f):
        dst = os.path.join(local_path, os.path.basename(f))
        client.download(f, dst)
        return dst
    with ThreadPoolExecutor(max_workers=multi_processes) as pool:
        return list(pool.map(pull, mine))


def multi_upload(client: HDFSClient, hdfs_path: str, local_path: str,
                 multi_processes: int = 5, overwrite: bool = False):
    """Upload every file under local_path concurrently (reference
    multi_upload). overwrite=False skips files already at the
    destination."""
    todo = []
    parents = set()
    for root, _dirs, files in os.walk(local_path):
        for f in files:
            src = os.path.join(root, f)
            rel = os.path.relpath(src, local_path)
            dst = os.path.join(hdfs_path, rel)
            parents.add(os.path.dirname(dst))
            todo.append((src, dst))
    # hadoop -put does not create missing parent dirs
    for p in sorted(parents):
        client.mkdir(p)

    def push(pair):
        src, dst = pair
        if not overwrite and client.is_exist(dst):
            return None
        client.upload(src, dst)
        return dst
    with ThreadPoolExecutor(max_workers=multi_processes) as pool:
        done = list(pool.map(push, todo))
    return [d for d in done if d is not None]
