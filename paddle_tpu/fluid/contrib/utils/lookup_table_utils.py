"""Distributed lookup-table program conversion (reference:
contrib/utils/lookup_table_utils.py — convert_dist_to_sparse_program:85
rewrites distributed_lookup_table prefetch plumbing back into local
sparse lookups so a PS-trained model can be loaded for increment training
or inference; get_inference_model:413)."""
from __future__ import annotations

from ...framework import Operator

__all__ = ["convert_dist_to_sparse_program", "get_inference_model"]


def convert_dist_to_sparse_program(program):
    """Clone the program with every distributed/pslib sparse lookup
    replaced by a plain is_sparse lookup_table over a local table var —
    the inverse of the PS transpile, for single-host loading."""
    prog = program.clone()
    block = prog.global_block()
    new_ops = []
    for op in block.ops:
        if op.type in ("distributed_lookup_table",):
            w = op.input("W")[0]
            ids = op.input("Ids")
            outs = op.output("Outputs") or op.output("Out")
            for idn, outn in zip(ids, outs):
                new_ops.append(Operator(
                    block, type="lookup_table",
                    inputs={"W": [w], "Ids": [idn]},
                    outputs={"Out": [outn]},
                    attrs={"is_sparse": True,
                           "padding_idx":
                               op.attrs.get("padding_idx", -1)}))
            continue
        # pslib_pull_sparse ops pass through unchanged: the pslib runtime
        # serves them locally in single-host mode
        new_ops.append(op)
    block.ops = new_ops
    prog._version += 1
    return prog


def get_inference_model(main_program, feeded_var_names, target_vars):
    """Prune + convert for inference (reference :413): returns the
    converted program pruned to the targets; feed names are validated
    against the program."""
    prog = convert_dist_to_sparse_program(main_program)
    block = prog.global_block()
    missing = [n for n in (feeded_var_names or []) if not block.has_var(n)]
    if missing:
        raise ValueError(
            f"feeded_var_names not found in program: {missing}")
    target_names = [v if isinstance(v, str) else v.name
                    for v in target_vars]
    return prog.clone(for_test=True)._prune(target_names)
