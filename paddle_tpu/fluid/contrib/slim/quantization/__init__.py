"""Quantization-aware training passes (reference:
python/paddle/fluid/contrib/slim/quantization/quantization_pass.py —
QuantizationTransformPass, QuantizationFreezePass; post_training_
quantization.py)."""
from .quantization_pass import (QuantizationTransformPass,
                                QuantizationFreezePass, quantize_program)
from .post_training_quantization import PostTrainingQuantization
from .quantization_strategy import QuantizationStrategy

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "quantize_program", "PostTrainingQuantization",
           "QuantizationStrategy"]
