"""QuantizationStrategy (reference: contrib/slim/quantization/
quantization_strategy.py) — applies the QAT transform at start_epoch and
freezes for inference export at end_epoch."""
from __future__ import annotations

from ..core.strategy import Strategy
from .quantization_pass import quantize_program

__all__ = ["QuantizationStrategy"]


class QuantizationStrategy(Strategy):
    def __init__(self, start_epoch: int = 0, end_epoch: int = 0,
                 weight_bits: int = 8, activation_bits: int = 8,
                 save_in_nodes=None, save_out_nodes=None,
                 float_model_save_path=None, int8_model_save_path=None):
        super().__init__(start_epoch, end_epoch)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.save_in_nodes = save_in_nodes
        self.save_out_nodes = save_out_nodes
        self.float_model_save_path = float_model_save_path
        self.int8_model_save_path = int8_model_save_path

    def on_epoch_begin(self, context):
        if context.epoch_id == self.start_epoch:
            quantize_program(context.train_graph,
                             weight_bits=self.weight_bits,
                             activation_bits=self.activation_bits)

    def on_epoch_end(self, context):
        if context.epoch_id == self.end_epoch and self.float_model_save_path:
            from ....executor import Executor, scope_guard
            from .... import io as fluid_io
            exe = Executor(context.place)
            block = context.train_graph.global_block()
            outs = [block.vars[n] for n in (self.save_out_nodes or [])]
            if outs:
                with scope_guard(context.scope):
                    fluid_io.save_inference_model(
                        self.float_model_save_path,
                        list(self.save_in_nodes or []), outs, exe,
                        main_program=context.train_graph)
