"""Post-training quantization (reference: contrib/slim/quantization/
post_training_quantization.py — PostTrainingQuantization:68: load model,
run calibration batches collecting per-tensor thresholds (abs_max / KL),
then rewrite the program with quant/dequant at the sampled scales and save).

TPU framing: the quantized program still executes as float math with
quantize→dequantize roundtrips (fake-quant), which XLA folds into the
surrounding ops — the artifact records int8 scales for deployment while
the simulation stays MXU-friendly."""
from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from .quantization_pass import QUANTIZABLE, _WEIGHT_SLOTS, _ACT_SLOTS

__all__ = ["PostTrainingQuantization"]


def _abs_max(samples: List[np.ndarray]) -> float:
    return float(max(np.abs(s).max() for s in samples)) or 1e-8


def _percentile(samples: List[np.ndarray], q: float = 99.99) -> float:
    flat = np.concatenate([np.abs(s).ravel() for s in samples])
    return float(np.percentile(flat, q)) or 1e-8


def _kl_threshold(samples: List[np.ndarray], bins: int = 2048,
                  levels: int = 128) -> float:
    """Entropy-calibrated threshold (reference _get_kl_scaling_factor):
    choose the clip that minimizes KL(P||Q) between the fp32 histogram and
    its quantized projection."""
    flat = np.abs(np.concatenate([s.ravel() for s in samples]))
    amax = float(flat.max()) or 1e-8
    hist, edges = np.histogram(flat, bins=bins, range=(0, amax))
    hist = hist.astype(np.float64)
    best_kl, best_i = None, bins - 1
    for i in range(levels, bins + 1, 8):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()  # clip outliers into last bin
        if p.sum() == 0:
            continue
        # quantize p into `levels` buckets then expand back
        factor = i / levels
        q = np.zeros(i)
        for l in range(levels):
            lo, hi = int(round(l * factor)), int(round((l + 1) * factor))
            hi = max(hi, lo + 1)
            chunk = p[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi][chunk > 0] = chunk.sum() / nz
        pn, qn = p / p.sum(), q / max(q.sum(), 1e-12)
        mask = pn > 0
        kl = float(np.sum(pn[mask] * np.log(pn[mask] /
                                            np.maximum(qn[mask], 1e-12))))
        if best_kl is None or kl < best_kl:
            best_kl, best_i = kl, i
    return float(edges[best_i])


_ALGOS = {"abs_max": _abs_max, "hist": _percentile, "KL": _kl_threshold}


class PostTrainingQuantization:
    """reference post_training_quantization.py:68.

    Either pass ``program`` (+ executor & scope holding trained params) or
    ``model_dir`` saved by save_inference_model. ``sample_generator`` yields
    feed dicts for calibration."""

    def __init__(self, executor, sample_generator,
                 model_dir: Optional[str] = None, program=None,
                 feed_names: Optional[Sequence[str]] = None,
                 fetch_names: Optional[Sequence[str]] = None,
                 scope=None, batch_nums: Optional[int] = 10,
                 algo: str = "KL",
                 quantizable_op_type: Optional[Sequence[str]] = None,
                 weight_bits: int = 8, activation_bits: int = 8):
        from ....executor import global_scope
        from .... import io as fluid_io
        if algo not in _ALGOS:
            raise ValueError(f"algo must be one of {sorted(_ALGOS)}")
        self._exe = executor
        self._scope = scope if scope is not None else global_scope()
        self._algo = algo
        self._batch_nums = batch_nums
        self._sample_generator = sample_generator
        self._wbits = weight_bits
        self._abits = activation_bits
        self._qtypes = set(quantizable_op_type or QUANTIZABLE)
        if model_dir is not None:
            from ....executor import scope_guard
            with scope_guard(self._scope):
                prog, feeds, fetches = fluid_io.load_inference_model(
                    model_dir, executor)
            self._program, self._feeds, self._fetches = prog, feeds, fetches
        else:
            if program is None:
                raise ValueError("need model_dir or program")
            self._program = program
            self._feeds = list(feed_names or [])
            self._fetches = list(fetch_names or [])
        self.scales: Dict[str, float] = {}

    # ------------------------------------------------------------------
    def _target_var_names(self):
        acts, weights = set(), set()
        persistable = {v.name for v in
                       self._program.global_block().vars.values()
                       if v.persistable}
        for op in self._program.global_block().ops:
            if op.type not in self._qtypes:
                continue
            w = _WEIGHT_SLOTS.get(op.type)
            a = _ACT_SLOTS.get(op.type)
            if w and op.input(w):
                (weights if op.input(w)[0] in persistable
                 else acts).add(op.input(w)[0])
            if a and op.input(a):
                acts.add(op.input(a)[0])
            for slot, names in op.outputs.items():
                acts.update(n for n in names if n not in persistable)
        return acts, weights

    def quantize(self):
        """Run calibration then rewrite the program (reference :264)."""
        from ....executor import scope_guard
        acts, weights = self._target_var_names()
        samples: Dict[str, List[np.ndarray]] = {n: [] for n in acts}
        fetch_names = sorted(acts)
        with scope_guard(self._scope):
            for i, feed in enumerate(self._sample_generator()):
                if self._batch_nums and i >= self._batch_nums:
                    break
                vals = self._exe.run(self._program, feed=feed,
                                     fetch_list=fetch_names)
                for n, v in zip(fetch_names, vals):
                    samples[n].append(np.asarray(v))
        algo_fn = _ALGOS[self._algo]
        for n, s in samples.items():
            if s:
                self.scales[n] = algo_fn(s)
        for n in weights:  # weights always abs_max per reference
            v = self._scope.find_var(n)
            if v is not None and v.is_initialized():
                self.scales[n] = _abs_max([np.asarray(
                    v.get_tensor().array)])
        self._rewrite()
        return self._program

    def _rewrite(self):
        """Insert fake_quantize_dequantize ops at the calibrated scales."""
        from ....framework import Operator
        from ....core import VarDesc
        from .... import unique_name
        import jax.numpy as jnp
        from ....core import LoDTensor
        block = self._program.global_block()
        new_ops: List = []
        quantized: Dict[str, str] = {}
        for op in block.ops:
            if op.type in self._qtypes:
                for slot_map, bits in ((_ACT_SLOTS, self._abits),
                                       (_WEIGHT_SLOTS, self._wbits)):
                    slot = slot_map.get(op.type)
                    if not slot or not op.input(slot):
                        continue
                    name = op.input(slot)[0]
                    if name not in self.scales:
                        continue
                    if name not in quantized:
                        qname = unique_name.generate(
                            name + ".quantized.dequantized")
                        src = block.vars.get(name)
                        block.create_var(
                            name=qname,
                            dtype=src.dtype if src else VarDesc.VarType.FP32,
                            shape=tuple(src.shape) if src else ())
                        sname = unique_name.generate(name + ".ptq_scale")
                        block.create_var(name=sname, shape=(1,),
                                         persistable=True,
                                         dtype=VarDesc.VarType.FP32)
                        self._scope.var(sname).set_value(LoDTensor(
                            jnp.asarray([self.scales[name]], jnp.float32)))
                        new_ops.append(Operator(
                            block, type="fake_quantize_dequantize_moving_average_abs_max",
                            inputs={"X": [name], "InScale": [sname]},
                            outputs={"Out": [qname], "OutScale": [sname]},
                            attrs={"bit_length": bits, "is_test": True}))
                        quantized[name] = qname
                    op.inputs[slot] = [quantized[name]]
            new_ops.append(op)
        # interleave: place each quant op right before its first consumer
        block.ops = []
        for op in new_ops:
            block.ops.append(op)
        self._program._version += 1

    def save_quantized_model(self, save_model_path: str):
        """reference :310 — export program+params with scales baked in."""
        from .... import io as fluid_io
        from ....executor import scope_guard
        with scope_guard(self._scope):
            block = self._program.global_block()
            targets = [block.vars[n] if not hasattr(n, "name") else n
                       for n in self._fetches]
            feed_names = [n if isinstance(n, str) else n.name
                          for n in self._feeds]
            fluid_io.save_inference_model(save_model_path, feed_names,
                                          targets, self._exe,
                                          main_program=self._program)
