"""QAT program transform (reference: contrib/slim/quantization/
quantization_pass.py — QuantizationTransformPass inserts fake_quant ops on
the inputs of quantizable ops; QuantizationFreezePass flips them to test
mode for inference export).

The reference rewrites an IrGraph; this build rewrites the Program
directly (the Program IS the graph here, and XLA does the rest). Weights
use quantize_dequantize_abs_max, activations use the moving-average
variant with a persistable scale state."""
from __future__ import annotations

from ....framework import Operator, default_main_program
from ....core import VarDesc
from .... import unique_name

QUANTIZABLE = {"conv2d", "depthwise_conv2d", "mul", "matmul", "matmul_v2"}
_WEIGHT_SLOTS = {"conv2d": "Filter", "depthwise_conv2d": "Filter",
                 "mul": "Y", "matmul": "Y", "matmul_v2": "Y"}
_ACT_SLOTS = {"conv2d": "Input", "depthwise_conv2d": "Input",
              "mul": "X", "matmul": "X", "matmul_v2": "X"}


def quantize_program(program=None, startup_program=None, weight_bits=8,
                     activation_bits=8, moving_rate=0.9,
                     quantizable_op_type=None, for_test=False):
    """Insert fake quant-dequant before every quantizable op's weight and
    activation input. Returns the (in-place modified) program."""
    import paddle_tpu.fluid as fluid
    program = program or default_main_program()
    startup = startup_program or fluid.default_startup_program()
    qtypes = set(quantizable_op_type or QUANTIZABLE)
    block = program.global_block()
    quantized = {}  # var name -> quantized var name (per program)
    new_ops = []
    params = {p.name for p in program.all_parameters()}
    for op in block.ops:
        if op.type in qtypes:
            for slot, bits, is_weight in (
                    (_ACT_SLOTS.get(op.type), activation_bits, False),
                    (_WEIGHT_SLOTS.get(op.type), weight_bits, True)):
                if slot is None or not op.input(slot):
                    continue
                name = op.input(slot)[0]
                if name in quantized:
                    op.inputs[slot] = [quantized[name]]
                    continue
                src = block.vars.get(name)
                qname = unique_name.generate(name + ".quantized.dequantized")
                qv = block.create_var(name=qname,
                                      dtype=src.dtype if src else
                                      VarDesc.VarType.FP32,
                                      shape=tuple(src.shape) if src else ())
                scale_name = unique_name.generate(name + ".quant_scale")
                sv = block.create_var(name=scale_name, shape=(1,),
                                      persistable=True,
                                      dtype=VarDesc.VarType.FP32)
                ssv = startup.global_block().create_var(
                    name=scale_name, shape=(1,), persistable=True,
                    dtype=VarDesc.VarType.FP32)
                startup.global_block().append_op(
                    type="fill_constant", inputs={},
                    outputs={"Out": [ssv]},
                    attrs={"shape": [1], "value": 0.0, "dtype": sv.dtype})
                if is_weight:
                    qop = Operator(
                        block, "fake_quantize_dequantize_abs_max",
                        inputs={"X": [name]},
                        outputs={"Out": [qname], "OutScale": [scale_name]},
                        attrs={"bit_length": bits})
                else:
                    qop = Operator(
                        block,
                        "fake_quantize_dequantize_moving_average_abs_max",
                        inputs={"X": [name], "InScale": [scale_name]},
                        outputs={"Out": [qname], "OutScale": [scale_name]},
                        attrs={"bit_length": bits,
                               "moving_rate": moving_rate,
                               "is_test": for_test})
                new_ops.append((op, qop))
                quantized[name] = qname
                op.inputs[slot] = [qname]
    # splice each quant op immediately before its consumer
    for consumer, qop in new_ops:
        idx = block.ops.index(consumer)
        block.ops.insert(idx, qop)
    return program


class QuantizationTransformPass:
    """reference QuantizationTransformPass — program-rewrite form."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, activation_quantize_type=
                 "moving_average_abs_max",
                 weight_quantize_type="abs_max", window_size=10000,
                 moving_rate=0.9, quantizable_op_type=None,
                 skip_pattern="skip_quant"):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate
        self.quantizable_op_type = quantizable_op_type

    def apply(self, program, startup_program=None, for_test=False):
        return quantize_program(
            program, startup_program, self.weight_bits,
            self.activation_bits, self.moving_rate,
            self.quantizable_op_type, for_test)


class QuantizationFreezePass:
    """reference QuantizationFreezePass — flip activation quant ops to
    test mode (frozen scales) for inference export."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, weight_quantize_type="abs_max"):
        pass

    def apply(self, program):
        for op in program.global_block().ops:
            if op.type.startswith("fake_quantize") and "is_test" in op.attrs:
                op.attrs["is_test"] = True
        return program
