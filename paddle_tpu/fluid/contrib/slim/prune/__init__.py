"""Model pruning (reference: python/paddle/fluid/contrib/slim/prune/)."""
from .pruner import Pruner, StructurePruner, RatioPruner
from .prune_strategy import (PruneStrategy, UniformPruneStrategy,
                             SensitivePruneStrategy, sensitivity)

__all__ = ["Pruner", "StructurePruner", "RatioPruner", "PruneStrategy",
           "UniformPruneStrategy", "SensitivePruneStrategy", "sensitivity"]
