"""Pruners — compute which filters/rows of a parameter to drop
(reference: contrib/slim/prune/pruner.py — Pruner:22, StructurePruner:34,
cal_pruned_idx:55, prune_tensor:81).

TPU design note: the reference physically shrinks tensors and patches every
downstream op's shape (graph surgery). On TPU, shape-changing surgery
re-triggers XLA compilation per ratio and produces MXU-unfriendly odd dims,
so the default here is masked (``lazy``) pruning — zeroing pruned channels
in place, keeping static shapes and letting sparsity show up as model-size
reduction at export. ``prune_tensor(lazy=False)`` still materializes the
physically smaller tensor for export paths."""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

__all__ = ["Pruner", "StructurePruner", "RatioPruner"]


class Pruner:
    """Base class (reference pruner.py:22)."""

    def prune(self, param, ratio: float):
        raise NotImplementedError


class StructurePruner(Pruner):
    """Structured (whole filter/row) pruning by importance criterion
    (reference pruner.py:34).

    pruning_axis: {param_name_or_"*": axis}
    criterions:   {param_name_or_"*": "l1_norm" | "l2_norm" | "random"}
    """

    def __init__(self, pruning_axis: Dict[str, int] = None,
                 criterions: Dict[str, str] = None):
        self.pruning_axis = pruning_axis or {"*": 0}
        self.criterions = criterions or {"*": "l1_norm"}

    def _axis(self, name: str) -> int:
        return self.pruning_axis.get(name, self.pruning_axis.get("*", 0))

    def _criterion(self, name: str) -> str:
        return self.criterions.get(name, self.criterions.get("*", "l1_norm"))

    def cal_pruned_idx(self, name: str, param: np.ndarray, ratio: float,
                       axis: int = None) -> List[int]:
        """Indices along ``axis`` to prune, lowest-importance first
        (reference pruner.py:55)."""
        axis = self._axis(name) if axis is None else axis
        crit = self._criterion(name)
        p = np.asarray(param, dtype=np.float64)
        reduce_axes = tuple(i for i in range(p.ndim) if i != axis)
        if crit == "l1_norm":
            scores = np.abs(p).sum(axis=reduce_axes)
        elif crit == "l2_norm":
            scores = np.sqrt((p * p).sum(axis=reduce_axes))
        elif crit == "random":
            scores = np.random.rand(p.shape[axis])
        else:
            raise ValueError(f"unknown criterion {crit}")
        n_prune = int(round(p.shape[axis] * ratio))
        order = np.argsort(scores, kind="stable")
        return sorted(order[:n_prune].tolist())

    def prune_tensor(self, tensor: np.ndarray, pruned_idx: Sequence[int],
                     pruned_axis: int, lazy: bool = True) -> np.ndarray:
        """lazy=True → zero the pruned slices (static shapes, TPU default);
        lazy=False → physically remove them (reference pruner.py:81)."""
        t = np.array(tensor)
        if lazy:
            sl = [slice(None)] * t.ndim
            sl[pruned_axis] = list(pruned_idx)
            t[tuple(sl)] = 0
            return t
        keep = [i for i in range(t.shape[pruned_axis]) if i not in
                set(pruned_idx)]
        return np.take(t, keep, axis=pruned_axis)

    def prune(self, param: np.ndarray, ratio: float, name: str = "*",
              lazy: bool = True) -> np.ndarray:
        idx = self.cal_pruned_idx(name, param, ratio)
        return self.prune_tensor(param, idx, self._axis(name), lazy=lazy)


class RatioPruner(Pruner):
    """Unstructured magnitude pruning to a target sparsity ratio."""

    def prune(self, param: np.ndarray, ratio: float) -> np.ndarray:
        p = np.array(param)
        k = int(round(p.size * ratio))
        if k == 0:
            return p
        thresh = np.partition(np.abs(p).ravel(), k - 1)[k - 1]
        p[np.abs(p) <= thresh] = 0
        return p
