"""Prune strategies (reference: contrib/slim/prune/prune_strategy.py —
PruneStrategy, UniformPruneStrategy, SensitivePruneStrategy;
auto_prune_strategy.py).

Strategies mutate the parameters living in a Scope (masked pruning — see
pruner.py for the TPU rationale) at the epochs the Compressor schedule
dictates."""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.strategy import Strategy
from .pruner import StructurePruner

__all__ = ["PruneStrategy", "UniformPruneStrategy",
           "SensitivePruneStrategy", "sensitivity"]


def _get_param(scope, name: str) -> np.ndarray:
    var = scope.find_var(name)
    if var is None or not var.is_initialized():
        raise KeyError(f"parameter '{name}' not found in scope")
    return np.asarray(var.get_tensor().array)


def _set_param(scope, name: str, value: np.ndarray):
    import jax.numpy as jnp
    from ....core import LoDTensor
    scope.var(name).set_value(LoDTensor(jnp.asarray(value)))


class PruneStrategy(Strategy):
    """Apply a pruner to listed params at ``start_epoch``
    (reference prune_strategy.py PruneStrategy)."""

    def __init__(self, pruner: Optional[StructurePruner] = None,
                 start_epoch: int = 0, end_epoch: int = 0,
                 params: Sequence[str] = (), ratios: Sequence[float] = ()):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner or StructurePruner()
        self.params = list(params)
        self.ratios = list(ratios)
        self._masks: Dict[str, np.ndarray] = {}

    def on_epoch_begin(self, context):
        if context.epoch_id == self.start_epoch:
            self._prune(context.scope)

    def on_batch_end(self, context):
        # re-apply masks so optimizer updates cannot resurrect pruned
        # channels (the reference re-writes shrunk tensors instead)
        scope = context.scope
        for name, mask in self._masks.items():
            _set_param(scope, name, _get_param(scope, name) * mask)

    def _prune(self, scope):
        for name, ratio in zip(self.params, self.ratios):
            p = _get_param(scope, name)
            idx = self.pruner.cal_pruned_idx(name, p, ratio)
            axis = self.pruner._axis(name)
            pruned = self.pruner.prune_tensor(p, idx, axis, lazy=True)
            mask = np.ones_like(p)
            sl = [slice(None)] * p.ndim
            sl[axis] = idx
            mask[tuple(sl)] = 0
            self._masks[name] = mask
            _set_param(scope, name, pruned)


class UniformPruneStrategy(PruneStrategy):
    """One ratio for every listed param (reference
    prune_strategy.py UniformPruneStrategy)."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 target_ratio=0.5, params: Sequence[str] = ()):
        super().__init__(pruner, start_epoch, end_epoch, params,
                         [target_ratio] * len(params))
        self.target_ratio = target_ratio


def sensitivity(program, scope, exe, params: Sequence[str],
                eval_func: Callable[[], float],
                ratios: Sequence[float] = (0.1, 0.3, 0.5, 0.7),
                pruner: Optional[StructurePruner] = None
                ) -> Dict[str, Dict[float, float]]:
    """Per-parameter sensitivity curve: metric loss at each prune ratio
    (reference sensitive_prune; restores the original weights after each
    probe)."""
    pruner = pruner or StructurePruner()
    result: Dict[str, Dict[float, float]] = {}
    baseline = eval_func()
    for name in params:
        orig = _get_param(scope, name).copy()
        curve: Dict[float, float] = {}
        for r in ratios:
            _set_param(scope, name, pruner.prune(orig, r, name=name))
            curve[r] = float(baseline - eval_func())
        _set_param(scope, name, orig)
        result[name] = curve
    return result


class SensitivePruneStrategy(PruneStrategy):
    """Pick per-param ratios from a sensitivity analysis so total pruning
    hits ``target_ratio`` while cheap-to-prune params take more of it
    (reference prune_strategy.py SensitivePruneStrategy)."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 target_ratio=0.5, params: Sequence[str] = (),
                 eval_func: Optional[Callable[[], float]] = None,
                 sensitivity_loss_bound: float = 0.05,
                 probe_ratios: Sequence[float] = (0.1, 0.3, 0.5, 0.7)):
        super().__init__(pruner, start_epoch, end_epoch, params, [])
        self.target_ratio = target_ratio
        self.eval_func = eval_func
        self.loss_bound = sensitivity_loss_bound
        self.probe_ratios = probe_ratios

    def on_epoch_begin(self, context):
        if context.epoch_id != self.start_epoch:
            return
        scope = context.scope
        if self.eval_func is None:
            self.ratios = [self.target_ratio] * len(self.params)
        else:
            sens = sensitivity(None, scope, None, self.params,
                               self.eval_func, self.probe_ratios,
                               self.pruner)
            self.ratios = []
            for name in self.params:
                curve = sens[name]
                ok = [r for r, loss in sorted(curve.items())
                      if loss <= self.loss_bound]
                self.ratios.append(max(ok) if ok else min(curve))
        self._prune(scope)
