"""SearchAgent — client side of the NAS controller service (reference:
contrib/slim/nas/search_agent.py)."""
from __future__ import annotations

import json
import socket

__all__ = ["SearchAgent"]


class SearchAgent:
    def __init__(self, server_ip: str, server_port: int,
                 key: str = "light-nas"):
        self._addr = (server_ip, server_port)
        self._key = key

    def _request(self, payload: dict) -> dict:
        payload["key"] = self._key
        with socket.create_connection(self._addr, timeout=30) as conn:
            conn.sendall((json.dumps(payload) + "\n").encode())
            return json.loads(conn.makefile("r").readline())

    def next_tokens(self):
        return self._request({"cmd": "next_tokens"})["tokens"]

    def update(self, tokens, reward: float) -> dict:
        return self._request({"cmd": "update", "tokens": list(tokens),
                              "reward": float(reward)})
