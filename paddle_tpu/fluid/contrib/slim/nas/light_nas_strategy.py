"""LightNASStrategy (reference: contrib/slim/nas/light_nas_strategy.py) —
simulated-annealing architecture search over a user SearchSpace, with an
optional latency constraint.

The reference splits controller (server) from trainers (agents) over a TCP
socket so multiple machines can evaluate tokens; the same server/agent pair
exists here (controller_server.py / search_agent.py) — this strategy runs
them in-process by default, which is the single-host TPU-VM case."""
from __future__ import annotations

from typing import Callable, Optional

from ..core.strategy import Strategy
from ..searcher.controller import SAController

__all__ = ["LightNASStrategy"]


class LightNASStrategy(Strategy):
    def __init__(self, controller: Optional[SAController] = None,
                 end_epoch: int = 0, target_latency: float = 0,
                 retrain_epoch: int = 0,
                 metric_name: str = "acc_top1",
                 server_ip: str = "", server_port: int = 0,
                 is_server: bool = True, max_client_num: int = 100,
                 search_steps: int = 10, key: str = "light-nas"):
        super().__init__(0, end_epoch)
        self._controller = controller or SAController()
        self.search_steps = search_steps
        self.target_latency = target_latency
        self.metric_name = metric_name
        self._server_ip = server_ip
        self._server_port = server_port
        self._is_server = is_server

    def search(self, search_space,
               eval_func: Optional[Callable] = None):
        """Run the SA search loop: for each step, sample tokens, build the
        net, score it (eval_func(train_prog, eval_prog, metrics) → reward),
        update the controller. Returns (best_tokens, best_reward)."""
        init = search_space.init_tokens()
        ranges = search_space.range_table()

        def constrain(tokens):
            if not self.target_latency:
                return True
            net = search_space.create_net(tokens)
            return search_space.get_model_latency(net[1]) \
                <= self.target_latency

        self._controller.reset(ranges, init, constrain)
        for step in range(self.search_steps):
            tokens = self._controller.next_tokens()
            net = search_space.create_net(tokens)
            if eval_func is not None:
                reward = float(eval_func(*net))
            else:
                reward = 0.0
            if self.target_latency:
                lat = search_space.get_model_latency(net[1])
                if lat > self.target_latency:
                    reward -= (lat - self.target_latency)
            self._controller.update(tokens, reward)
        return self._controller.best_tokens, self._controller.max_reward

    def on_compression_begin(self, context):
        context.search_strategy = self
