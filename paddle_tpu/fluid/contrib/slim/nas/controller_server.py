"""ControllerServer — serve a controller to remote search agents over TCP
(reference: contrib/slim/nas/controller_server.py; line protocol
"next_tokens" / "update <reward> <tokens...>")."""
from __future__ import annotations

import json
import socket
import threading
from typing import Optional

__all__ = ["ControllerServer"]


class ControllerServer:
    def __init__(self, controller, address=("127.0.0.1", 0),
                 max_client_num: int = 100, search_steps: int = 10,
                 key: str = "light-nas"):
        self._controller = controller
        self._address = address
        self._max_client_num = max_client_num
        self._key = key
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._lock = threading.Lock()

    def start(self):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(self._address)
        self._sock.listen(self._max_client_num)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def ip(self):
        return self._sock.getsockname()[0]

    def port(self):
        return self._sock.getsockname()[1]

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with conn:
                data = conn.makefile("r").readline()
                if not data:
                    continue
                try:
                    req = json.loads(data)
                except json.JSONDecodeError:
                    continue
                if req.get("key") != self._key:
                    conn.sendall(b'{"error": "bad key"}\n')
                    continue
                with self._lock:
                    if req.get("cmd") == "next_tokens":
                        resp = {"tokens": self._controller.next_tokens()}
                    elif req.get("cmd") == "update":
                        self._controller.update(req["tokens"],
                                                float(req["reward"]))
                        resp = {"ok": True,
                                "best_tokens": self._controller.best_tokens,
                                "max_reward": self._controller.max_reward}
                    else:
                        resp = {"error": "unknown cmd"}
                conn.sendall((json.dumps(resp) + "\n").encode())
