"""Light-NAS (reference: contrib/slim/nas/)."""
from .search_space import SearchSpace
from .light_nas_strategy import LightNASStrategy
from .controller_server import ControllerServer
from .search_agent import SearchAgent

__all__ = ["SearchSpace", "LightNASStrategy", "ControllerServer",
           "SearchAgent"]
