"""SearchSpace contract the user implements (reference:
contrib/slim/nas/search_space.py)."""
from __future__ import annotations

__all__ = ["SearchSpace"]


class SearchSpace:
    """Subclass and implement the four methods (reference search_space.py):
    init_tokens / range_table define the token space; create_net builds the
    train/eval programs for a token vector; get_model_latency scores cost."""

    def init_tokens(self):
        raise NotImplementedError

    def range_table(self):
        raise NotImplementedError

    def create_net(self, tokens=None):
        """Return (startup_program, train_program, eval_program,
        train_metrics, eval_metrics) for the given tokens."""
        raise NotImplementedError

    def get_model_latency(self, program) -> float:
        """Optional cost model used by the latency constraint."""
        return 0.0
