"""slim — model compression toolkit (reference:
python/paddle/fluid/contrib/slim/): quantization (QAT + post-training),
structured/unstructured pruning, knowledge distillation, light-NAS with a
simulated-annealing controller, all driven by the Compressor epoch loop."""
from . import core  # noqa: F401
from . import quantization  # noqa: F401
from . import prune  # noqa: F401
from . import distillation  # noqa: F401
from . import nas  # noqa: F401
from . import searcher  # noqa: F401
from .core import Compressor  # noqa: F401
