"""Distillers — build the distillation loss into the student program
(reference: contrib/slim/distillation/distiller.py — L2Distiller:25,
FSPDistiller:103, SoftLabelDistiller:195; each has an IrGraph "Pass" that
appends loss ops and sums with the existing loss).

Here the student program IS the graph; ``merge_teacher_program`` clones the
teacher's ops/vars into it under a ``teacher_`` prefix (the reference
merges IrGraphs the same way), then the distillers append loss ops."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

__all__ = ["merge_teacher_program", "L2Distiller", "FSPDistiller",
           "SoftLabelDistiller"]

TEACHER_PREFIX = "teacher_"


def merge_teacher_program(teacher_program, student_program,
                          data_name_map: Optional[Dict[str, str]] = None,
                          name_prefix: str = TEACHER_PREFIX) -> Dict[str, str]:
    """Clone teacher ops+vars into the student program, renaming every
    teacher var ``name_prefix+name`` except feed data vars, which map onto
    the student's own data vars via ``data_name_map`` {teacher: student}.
    Returns {teacher_var: merged_name}. Teacher persistables must then be
    loaded into the scope under their prefixed names."""
    data_name_map = data_name_map or {}
    tb = teacher_program.global_block()
    sb = student_program.global_block()
    rename: Dict[str, str] = {}
    for name, var in tb.vars.items():
        if name in data_name_map:
            rename[name] = data_name_map[name]
            continue
        new = name_prefix + name
        rename[name] = new
        if new not in sb.vars:
            sb.create_var(name=new, shape=tuple(var.shape), dtype=var.dtype,
                          persistable=var.persistable,
                          stop_gradient=True, lod_level=var.lod_level)
    for op in tb.ops:
        if op.type in ("feed", "fetch"):
            continue
        ins = {s: [rename.get(n, name_prefix + n) for n in ns]
               for s, ns in op.inputs.items()}
        outs = {s: [rename.get(n, name_prefix + n) for n in ns]
                for s, ns in op.outputs.items()}
        sb.append_op(type=op.type, inputs=ins, outputs=outs,
                     attrs=dict(op.attrs))
    return rename


class L2Distiller:
    """MSE between a student feature var and a teacher feature var
    (reference distiller.py:25)."""

    def __init__(self, student_feature_map: str, teacher_feature_map: str,
                 distillation_loss_weight: float = 1.0):
        self.student = student_feature_map
        self.teacher = teacher_feature_map
        self.weight = distillation_loss_weight

    def distiller_loss(self, program):
        from .... import layers
        b = program.global_block()
        s, t = b.vars[self.student], b.vars[self.teacher]
        diff = layers.elementwise_sub(s, t)
        loss = layers.reduce_mean(layers.elementwise_mul(diff, diff))
        return layers.scale(loss, self.weight)


class FSPDistiller:
    """Flow-of-solution-procedure loss over (layer-pair) feature maps
    (reference distiller.py:103; fsp op — operators/fsp_op.cc)."""

    def __init__(self, student_pairs: Sequence[Sequence[str]],
                 teacher_pairs: Sequence[Sequence[str]],
                 distillation_loss_weight: float = 1.0):
        self.student_pairs = student_pairs
        self.teacher_pairs = teacher_pairs
        self.weight = distillation_loss_weight

    def distiller_loss(self, program):
        from .... import layers
        b = program.global_block()
        losses = []
        for (s0, s1), (t0, t1) in zip(self.student_pairs,
                                      self.teacher_pairs):
            s_fsp = layers.fsp_matrix(b.vars[s0], b.vars[s1])
            t_fsp = layers.fsp_matrix(b.vars[t0], b.vars[t1])
            diff = layers.elementwise_sub(s_fsp, t_fsp)
            losses.append(
                layers.reduce_mean(layers.elementwise_mul(diff, diff)))
        total = losses[0]
        for l in losses[1:]:
            total = layers.elementwise_add(total, l)
        return layers.scale(total, self.weight)


class SoftLabelDistiller:
    """Cross-entropy of temperature-softened teacher logits against
    student logits (reference distiller.py:195)."""

    def __init__(self, student_feature_map: str, teacher_feature_map: str,
                 student_temperature: float = 1.0,
                 teacher_temperature: float = 1.0,
                 distillation_loss_weight: float = 1.0):
        self.student = student_feature_map
        self.teacher = teacher_feature_map
        self.st = student_temperature
        self.tt = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, program):
        from .... import layers
        b = program.global_block()
        s = layers.softmax(layers.scale(b.vars[self.student], 1.0 / self.st))
        t = layers.softmax(layers.scale(b.vars[self.teacher], 1.0 / self.tt))
        loss = layers.reduce_mean(
            layers.cross_entropy(s, t, soft_label=True))
        return layers.scale(loss, self.weight)
