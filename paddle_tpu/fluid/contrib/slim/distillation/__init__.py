"""Knowledge distillation (reference: contrib/slim/distillation/)."""
from .distiller import (L2Distiller, FSPDistiller, SoftLabelDistiller,
                        merge_teacher_program)
from .distillation_strategy import DistillationStrategy

__all__ = ["L2Distiller", "FSPDistiller", "SoftLabelDistiller",
           "merge_teacher_program", "DistillationStrategy"]
