"""DistillationStrategy (reference: contrib/slim/distillation/
distillation_strategy.py) — at start_epoch, appends the distiller losses to
the training loss; the Compressor then trains on the combined objective."""
from __future__ import annotations

from typing import Sequence

from ..core.strategy import Strategy

__all__ = ["DistillationStrategy"]


class DistillationStrategy(Strategy):
    def __init__(self, distillers: Sequence = (), start_epoch: int = 0,
                 end_epoch: int = 0):
        super().__init__(start_epoch, end_epoch)
        self.distillers = list(distillers)

    def on_compression_begin(self, context):
        from ....framework import program_guard
        from .... import layers
        program = context.train_graph
        with program_guard(program):
            losses = [d.distiller_loss(program) for d in self.distillers]
            total = losses[0]
            for l in losses[1:]:
                total = layers.elementwise_add(total, l)
            context.distill_loss = total
