"""Evolutionary search controllers (reference: contrib/slim/searcher/)."""
from .controller import EvolutionaryController, SAController

__all__ = ["EvolutionaryController", "SAController"]
