"""Search controllers (reference: contrib/slim/searcher/controller.py —
EvolutionaryController:28, SAController:59 simulated annealing over integer
token vectors)."""
from __future__ import annotations

import math
import random
from typing import Callable, List, Optional, Sequence

__all__ = ["EvolutionaryController", "SAController"]


class EvolutionaryController:
    """reference controller.py:28."""

    def update(self, tokens: Sequence[int], reward: float):
        raise NotImplementedError

    def reset(self, range_table: Sequence[int], init_tokens: Sequence[int],
              constrain_func: Optional[Callable] = None):
        raise NotImplementedError

    def next_tokens(self) -> List[int]:
        raise NotImplementedError


class SAController(EvolutionaryController):
    """Simulated annealing (reference controller.py:59): propose a mutated
    token vector; accept if reward improves, else with probability
    exp((reward - best) / temperature)."""

    def __init__(self, range_table: Optional[Sequence[int]] = None,
                 reduce_rate: float = 0.85, init_temperature: float = 1024,
                 max_iter_number: int = 300, seed: Optional[int] = None):
        self._range_table = list(range_table or [])
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._iter = 0
        self._temperature = init_temperature
        self._tokens: List[int] = []
        self._reward = -float("inf")
        self._best_tokens: List[int] = []
        self._max_reward = -float("inf")
        self._constrain_func: Optional[Callable] = None
        self._rng = random.Random(seed)

    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_constrain_func", None)
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._constrain_func = None

    def reset(self, range_table: Sequence[int],
              init_tokens: Sequence[int],
              constrain_func: Optional[Callable] = None):
        self._range_table = list(range_table)
        self._tokens = list(init_tokens)
        self._best_tokens = list(init_tokens)
        self._constrain_func = constrain_func
        self._iter = 0
        self._temperature = self._init_temperature
        self._reward = -float("inf")
        self._max_reward = -float("inf")

    def update(self, tokens: Sequence[int], reward: float):
        """Accept/reject ``tokens`` given its measured ``reward``."""
        self._iter += 1
        temperature = self._init_temperature * (
            self._reduce_rate ** self._iter)
        self._temperature = temperature
        if (reward > self._reward
                or self._rng.random() < math.exp(
                    min((reward - self._reward) / max(temperature, 1e-9),
                        0.0))):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self) -> List[int]:
        """Mutate the current tokens (reference: flips one random slot)."""
        for _ in range(100):
            tokens = list(self._tokens)
            i = self._rng.randrange(len(tokens))
            tokens[i] = self._rng.randrange(self._range_table[i])
            if self._constrain_func is None or self._constrain_func(tokens):
                return tokens
        return list(self._tokens)

    @property
    def best_tokens(self):
        return list(self._best_tokens)

    @property
    def max_reward(self):
        return self._max_reward

    @property
    def current_tokens(self):
        return list(self._tokens)
