"""Strategy base (reference: contrib/slim/core/strategy.py) — epoch/batch
hooks the Compressor drives."""
from __future__ import annotations

__all__ = ["Strategy"]


class Strategy:
    def __init__(self, start_epoch: int = 0, end_epoch: int = 0):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_compression_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_batch_begin(self, context):
        pass

    def on_batch_end(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_compression_end(self, context):
        pass
