"""Compressor — the slim training driver (reference:
contrib/slim/core/compressor.py Compressor/Context): runs the train program
epoch by epoch, invoking each strategy's hooks, evaluating and
checkpointing. The TPU build keeps the same control surface; the step
itself is the compiled executor step."""
from __future__ import annotations

import os
import pickle
from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np

from .strategy import Strategy

__all__ = ["Context", "Compressor"]


class Context:
    """Shared state handed to strategy hooks (reference compressor.py
    Context)."""

    def __init__(self, place, scope, train_graph=None, eval_graph=None,
                 train_reader=None, eval_reader=None, optimizer=None):
        self.place = place
        self.scope = scope
        self.train_graph = train_graph
        self.eval_graph = eval_graph
        self.train_reader = train_reader
        self.eval_reader = eval_reader
        self.optimizer = optimizer
        self.epoch_id = 0
        self.batch_id = 0
        self.eval_results = {}

    def run_eval_graph(self):
        raise NotImplementedError(
            "provide eval via Compressor(eval_func=...)")


class Compressor:
    """reference compressor.py Compressor — config-driven epoch loop."""

    def __init__(self, place, scope, train_program, train_reader=None,
                 train_feed_list: Optional[Sequence[str]] = None,
                 train_fetch_list: Optional[Sequence] = None,
                 eval_program=None, eval_reader=None,
                 eval_feed_list: Optional[Sequence[str]] = None,
                 eval_fetch_list: Optional[Sequence] = None,
                 eval_func: Optional[Callable[[], float]] = None,
                 teacher_programs: Sequence = (), optimizer=None,
                 epoch: int = 1, checkpoint_path: Optional[str] = None):
        self.place = place
        self.scope = scope
        self.train_program = train_program
        self.train_reader = train_reader
        self.train_feed_list = list(train_feed_list or [])
        self.train_fetch_list = list(train_fetch_list or [])
        self.eval_program = eval_program
        self.eval_reader = eval_reader
        self.eval_feed_list = list(eval_feed_list or [])
        self.eval_fetch_list = list(eval_fetch_list or [])
        self.eval_func = eval_func
        self.teacher_programs = list(teacher_programs)
        self.optimizer = optimizer
        self.epoch = epoch
        self.checkpoint_path = checkpoint_path
        self.strategies: List[Strategy] = []

    def config(self, config_or_strategies):
        """Accept a list of strategies or a ConfigFactory result."""
        from .config import ConfigFactory
        if isinstance(config_or_strategies, ConfigFactory):
            self.strategies = config_or_strategies.strategies
            self.epoch = max(self.epoch, config_or_strategies.epoch)
        elif isinstance(config_or_strategies, str):
            fac = ConfigFactory(config_or_strategies)
            self.strategies = fac.strategies
            self.epoch = max(self.epoch, fac.epoch)
        else:
            self.strategies = list(config_or_strategies)
        return self

    # ------------------------------------------------------------------
    def run(self):
        from ....executor import Executor, scope_guard
        exe = Executor(self.place)
        ctx = Context(self.place, self.scope,
                      train_graph=self.train_program,
                      eval_graph=self.eval_program,
                      train_reader=self.train_reader,
                      eval_reader=self.eval_reader,
                      optimizer=self.optimizer)
        for s in self.strategies:
            s.on_compression_begin(ctx)
        with scope_guard(self.scope):
            for epoch_id in range(self.epoch):
                ctx.epoch_id = epoch_id
                for s in self.strategies:
                    s.on_epoch_begin(ctx)
                if self.train_reader is not None:
                    for batch_id, data in enumerate(self.train_reader()):
                        ctx.batch_id = batch_id
                        for s in self.strategies:
                            s.on_batch_begin(ctx)
                        feed = data if isinstance(data, dict) else dict(
                            zip(self.train_feed_list, data))
                        ctx.last_fetch = exe.run(
                            self.train_program, feed=feed,
                            fetch_list=self.train_fetch_list)
                        for s in self.strategies:
                            s.on_batch_end(ctx)
                if self.eval_func is not None:
                    ctx.eval_results.setdefault("metric", []).append(
                        float(self.eval_func()))
                for s in self.strategies:
                    s.on_epoch_end(ctx)
                if self.checkpoint_path:
                    self._save_checkpoint(epoch_id)
        for s in self.strategies:
            s.on_compression_end(ctx)
        return ctx

    def _save_checkpoint(self, epoch_id: int):
        os.makedirs(self.checkpoint_path, exist_ok=True)
        params = {}
        for v in self.train_program.global_block().vars.values():
            if v.persistable:
                sv = self.scope.find_var(v.name)
                if sv is not None and sv.is_initialized():
                    params[v.name] = np.asarray(sv.get_tensor().array)
        with open(os.path.join(self.checkpoint_path,
                               f"epoch_{epoch_id}.pkl"), "wb") as f:
            pickle.dump({"epoch": epoch_id, "params": params}, f)
