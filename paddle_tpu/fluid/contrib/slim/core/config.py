"""Config loader for slim strategies (reference: contrib/slim/core/config.py
ConfigFactory — YAML of strategy class names + kwargs). Accepts a dict (or
YAML text if pyyaml happens to be importable) of the same shape:

    {"strategies": {"prune_0": {"class": "UniformPruneStrategy",
                                "target_ratio": 0.5, ...}},
     "compressor": {"epoch": 10, "strategies": ["prune_0"]}}
"""
from __future__ import annotations

from typing import Any, Dict

__all__ = ["ConfigFactory"]


def _strategy_registry():
    from ..prune import (PruneStrategy, UniformPruneStrategy,
                         SensitivePruneStrategy)
    from ..distillation import DistillationStrategy
    from ..quantization import QuantizationStrategy
    from ..nas import LightNASStrategy
    return {c.__name__: c for c in (
        PruneStrategy, UniformPruneStrategy, SensitivePruneStrategy,
        DistillationStrategy, QuantizationStrategy, LightNASStrategy)}


class ConfigFactory:
    def __init__(self, config):
        if isinstance(config, str):
            try:
                import yaml
            except ImportError as e:
                raise ImportError(
                    "string configs need pyyaml; pass a dict instead") from e
            config = yaml.safe_load(open(config) if "\n" not in config
                                    else config)
        self._build(config)

    def _build(self, cfg: Dict[str, Any]):
        reg = _strategy_registry()
        defined = {}
        for name, spec in (cfg.get("strategies") or {}).items():
            spec = dict(spec)
            cls = reg[spec.pop("class")]
            defined[name] = cls(**spec)
        comp = cfg.get("compressor") or {}
        order = comp.get("strategies") or list(defined)
        self.strategies = [defined[n] for n in order]
        self.epoch = int(comp.get("epoch", 1))
