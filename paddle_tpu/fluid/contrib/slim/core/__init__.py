"""slim core (reference: contrib/slim/core/)."""
from .strategy import Strategy
from .compressor import Compressor, Context
from .config import ConfigFactory

__all__ = ["Strategy", "Compressor", "Context", "ConfigFactory"]
