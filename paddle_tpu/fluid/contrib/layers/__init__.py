"""contrib.layers (reference: contrib/layers/ — nn.py specialty-op
wrappers, rnn_impl.py basic GRU/LSTM, metric_op.py ctr metric bundle)."""
from .nn import (fused_elemwise_activation, var_conv_2d,
                 match_matrix_tensor, sequence_topk_avg_pooling, tree_conv,
                 fused_embedding_seq_pool, multiclass_nms2, shuffle_batch,
                 partial_concat, partial_sum, rank_attention, batch_fc)
from .rnn_impl import BasicGRUUnit, BasicLSTMUnit, basic_gru, basic_lstm
from .metric_op import ctr_metric_bundle

__all__ = [
    "fused_elemwise_activation", "var_conv_2d", "match_matrix_tensor",
    "sequence_topk_avg_pooling", "tree_conv", "fused_embedding_seq_pool",
    "multiclass_nms2", "shuffle_batch", "partial_concat", "partial_sum",
    "rank_attention", "batch_fc", "BasicGRUUnit", "BasicLSTMUnit",
    "basic_gru", "basic_lstm", "ctr_metric_bundle",
]
