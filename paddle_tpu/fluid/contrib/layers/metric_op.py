"""ctr_metric_bundle (reference: contrib/layers/metric_op.py — emits the
stat variables FleetUtil.get_global_metrics consumes: squared error, abs
error, prob sum, q sum, pos/total instance counts)."""
from __future__ import annotations

from ... import layers
from ...layer_helper import LayerHelper
from ...core import VarDesc

__all__ = ["ctr_metric_bundle"]


def ctr_metric_bundle(input, label):
    """input: predicted ctr [B,1] float; label: [B,1] float 0/1. Returns
    (sqrerr, abserr, prob, q, pos_num, total_num) accumulator vars —
    persistable running sums matching the reference contract."""
    helper = LayerHelper("ctr_metric_bundle")

    def acc_var(name):
        block = helper.main_program.global_block()
        v = block.create_var(name=helper.name + "_" + name, shape=(1,),
                             dtype=VarDesc.VarType.FP32, persistable=True)
        from ...framework import default_startup_program
        sb = default_startup_program().global_block()
        sb.create_var(name=v.name, shape=(1,), persistable=True,
                      dtype=VarDesc.VarType.FP32)
        sb.append_op(type="fill_constant", inputs={}, outputs={"Out": [v]},
                     attrs={"shape": [1], "value": 0.0,
                            "dtype": VarDesc.VarType.FP32})
        return v

    diff = layers.elementwise_sub(input, label)
    batch_sqrerr = layers.reduce_sum(
        layers.elementwise_mul(diff, diff))
    batch_abserr = layers.reduce_sum(layers.abs(diff))
    batch_prob = layers.reduce_sum(input)
    batch_q = layers.reduce_sum(
        layers.elementwise_mul(input, label))
    batch_pos = layers.reduce_sum(label)
    batch_total = layers.reduce_sum(layers.ones_like(label))

    outs = []
    for name, batch in (("sqrerr", batch_sqrerr), ("abserr", batch_abserr),
                        ("prob", batch_prob), ("q", batch_q),
                        ("pos", batch_pos), ("total", batch_total)):
        acc = acc_var(name)
        b1 = layers.reshape(batch, [1])
        helper.append_op(type="elementwise_add",
                         inputs={"X": [acc], "Y": [b1]},
                         outputs={"Out": [acc]}, attrs={"axis": -1})
        outs.append(acc)
    return tuple(outs)
