"""contrib specialty-op wrappers (reference: contrib/layers/nn.py:33-760 —
builders for the fused/search/ads ops; the kernels live in the op set)."""
from __future__ import annotations

from ...layer_helper import LayerHelper
from ...core import VarDesc

__all__ = [
    "fused_elemwise_activation", "var_conv_2d", "match_matrix_tensor",
    "sequence_topk_avg_pooling", "tree_conv", "fused_embedding_seq_pool",
    "multiclass_nms2", "shuffle_batch", "partial_concat", "partial_sum",
    "rank_attention", "batch_fc",
]


def _op(op_type, ins, attrs=None, out_slots=("Out",), dtype=None):
    helper = LayerHelper(op_type)
    if dtype is None:
        dtype = next((v.dtype for vals in ins.values() for v in vals
                      if v is not None and hasattr(v, "dtype")),
                     VarDesc.VarType.FP32)
    outs = {s: [helper.create_variable_for_type_inference(dtype)]
            for s in out_slots}
    helper.append_op(type=op_type, inputs=ins, outputs=outs,
                     attrs=attrs or {})
    vals = [outs[s][0] for s in out_slots]
    return vals[0] if len(vals) == 1 else tuple(vals)


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """reference contrib/layers/nn.py:41."""
    return _op("fused_elemwise_activation", {"X": [x], "Y": [y]},
               {"functor_list": list(functor_list), "axis": axis,
                "scale": scale,
                "save_intermediate_out": save_intermediate_out},
               out_slots=("Out",))


def var_conv_2d(input, row, col, input_channel, output_channel,
                filter_size, stride=1, param_attr=None, act=None,
                dtype="float32", name=None):
    """reference contrib/layers/nn.py:105 — conv over variable-sized 2D
    feature maps described by ROW/COLUMN LoD."""
    helper = LayerHelper("var_conv_2d", name=name)
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    st = stride if isinstance(stride, (list, tuple)) else [stride, stride]
    w = helper.create_parameter(
        attr=helper.param_attr if param_attr is None else param_attr,
        shape=[output_channel, input_channel * fs[0] * fs[1]],
        dtype=dtype)
    out = _op("var_conv_2d",
              {"X": [input], "ROW": [row], "COLUMN": [col], "W": [w]},
              {"InputChannel": input_channel,
               "OutputChannel": output_channel,
               "StrideH": st[0], "StrideW": st[1],
               "KernelH": fs[0], "KernelW": fs[1]})
    return helper.append_activation(out) if act else out


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None):
    """reference contrib/layers/nn.py:222."""
    helper = LayerHelper("match_matrix_tensor", name=name)
    w = helper.create_parameter(
        attr=helper.param_attr if param_attr is None else param_attr,
        shape=[int(x.shape[-1]), channel_num, int(y.shape[-1])],
        dtype=dtype)
    out, tmp = _op("match_matrix_tensor",
                   {"X": [x], "Y": [y], "W": [w]},
                   {"dim_t": channel_num},
                   out_slots=("Out", "Tmp"))
    return (helper.append_activation(out) if act else out), tmp


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    """reference contrib/layers/nn.py:309."""
    return _op("sequence_topk_avg_pooling",
               {"X": [input], "ROW": [row], "COLUMN": [col]},
               {"topks": list(topks), "channel_num": channel_num},
               out_slots=("Out",))


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """reference contrib/layers/nn.py:377."""
    helper = LayerHelper("tree_conv", name=name)
    dtype = nodes_vector.dtype
    w = helper.create_parameter(
        attr=helper.param_attr if param_attr is None else param_attr,
        shape=[int(nodes_vector.shape[-1]), 3, output_size, num_filters],
        dtype=dtype)
    out = _op("tree_conv",
              {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
               "Filter": [w]},
              {"max_depth": max_depth}, out_slots=("Out",))
    return helper.append_activation(out) if act else out


def fused_embedding_seq_pool(input, size, is_sparse=False,
                             padding_idx=None, combiner="sum",
                             param_attr=None, dtype="float32"):
    """reference contrib/layers/nn.py:447."""
    helper = LayerHelper("fused_embedding_seq_pool")
    w = helper.create_parameter(
        attr=helper.param_attr if param_attr is None else param_attr,
        shape=list(size), dtype=dtype)
    return _op("fused_embedding_seq_pool", {"W": [w], "Ids": [input]},
               {"combiner": combiner, "is_sparse": is_sparse,
                "padding_idx": -1 if padding_idx is None else padding_idx})


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k,
                    keep_top_k, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=0,
                    return_index=False, name=None):
    """reference contrib/layers/nn.py:514."""
    if return_index:
        raise NotImplementedError(
            "multiclass_nms2(return_index=True): the kernel does not "
            "emit the Index output yet — use the Out tensor")
    helper = LayerHelper("multiclass_nms2", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    index = helper.create_variable_for_type_inference(
        VarDesc.VarType.INT32)
    helper.append_op(
        type="multiclass_nms2",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out], "Index": [index]},
        attrs={"score_threshold": score_threshold,
               "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
               "nms_threshold": nms_threshold, "normalized": normalized,
               "nms_eta": nms_eta, "background_label": background_label})
    return (out, index) if return_index else out


def shuffle_batch(x, seed=None):
    """reference contrib/layers/nn.py shuffle_batch."""
    ins = {"X": [x]}
    attrs = {}
    if isinstance(seed, int):
        attrs["startup_seed"] = seed
    return _op("shuffle_batch", ins, attrs,
               out_slots=("Out",))


def partial_concat(input, start_index=0, length=-1):
    """reference contrib/layers/nn.py partial_concat."""
    return _op("partial_concat", {"X": list(input)},
               {"start_index": start_index, "length": length})


def partial_sum(input, start_index=0, length=-1):
    return _op("partial_sum", {"X": list(input)},
               {"start_index": start_index, "length": length})


def rank_attention(input, rank_offset, rank_param_shape, rank_param_attr,
                   max_rank=3):
    """reference contrib/layers/nn.py rank_attention (ads ranking)."""
    helper = LayerHelper("rank_attention")
    w = helper.create_parameter(attr=rank_param_attr,
                                shape=list(rank_param_shape),
                                dtype=input.dtype)
    return _op("rank_attention",
               {"X": [input], "RankOffset": [rank_offset],
                "RankParam": [w]},
               {"MaxRank": max_rank}, out_slots=("Out",))


def batch_fc(input, param_size, param_attr, bias_size, bias_attr,
             act=None):
    """reference contrib/layers/nn.py batch_fc (per-batch-slot fc)."""
    helper = LayerHelper("batch_fc")
    w = helper.create_parameter(attr=param_attr, shape=list(param_size),
                                dtype=input.dtype)
    b = helper.create_parameter(attr=bias_attr, shape=list(bias_size),
                                dtype=input.dtype)
    out = _op("batch_fc", {"Input": [input], "W": [w], "Bias": [b]})
    return helper.append_activation(out) if act else out
