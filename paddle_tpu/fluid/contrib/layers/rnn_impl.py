"""Basic GRU/LSTM built from elementary ops (reference:
contrib/layers/rnn_impl.py — BasicGRUUnit/BasicLSTMUnit dygraph-style
units plus basic_gru/basic_lstm sequence runners; here the sequence loop
is the framework's StaticRNN unroll → lax.scan under XLA)."""
from __future__ import annotations

from ... import layers
from ...dygraph import Layer
from ...param_attr import ParamAttr

__all__ = ["BasicGRUUnit", "BasicLSTMUnit", "basic_gru", "basic_lstm"]


class BasicGRUUnit(Layer):
    """One GRU step (reference rnn_impl.py BasicGRUUnit)."""

    def __init__(self, name_scope, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 dtype="float32"):
        super().__init__(name_scope)
        self._hidden_size = hidden_size
        self._gate_act = gate_activation or layers.sigmoid
        self._act = activation or layers.tanh
        self._dtype = dtype
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._built = False

    def _build_once(self, input):
        in_dim = int(input.shape[-1])
        H = self._hidden_size
        self._gate_w = self.create_parameter(
            [in_dim + H, 2 * H], attr=self._param_attr, dtype=self._dtype)
        self._gate_b = self.create_parameter(
            [2 * H], attr=self._bias_attr, dtype=self._dtype, is_bias=True)
        self._cand_w = self.create_parameter(
            [in_dim + H, H], attr=self._param_attr, dtype=self._dtype)
        self._cand_b = self.create_parameter(
            [H], attr=self._bias_attr, dtype=self._dtype, is_bias=True)
        self._built = True

    def forward(self, input, pre_hidden):
        if not self._built:
            self._build_once(input)
        concat = layers.concat([input, pre_hidden], axis=1)
        gates = layers.elementwise_add(
            layers.matmul(concat, self._gate_w), self._gate_b)
        # reference gate order: (reset, update)
        r, u = layers.split(self._gate_act(gates), 2, dim=1)
        r_hidden = layers.elementwise_mul(r, pre_hidden)
        cand = self._act(layers.elementwise_add(
            layers.matmul(layers.concat([input, r_hidden], axis=1),
                          self._cand_w), self._cand_b))
        one_minus_u = layers.scale(u, scale=-1.0, bias=1.0)
        return layers.elementwise_add(
            layers.elementwise_mul(pre_hidden, u),
            layers.elementwise_mul(cand, one_minus_u))


class BasicLSTMUnit(Layer):
    """One LSTM step (reference rnn_impl.py BasicLSTMUnit)."""

    def __init__(self, name_scope, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 forget_bias=1.0, dtype="float32"):
        super().__init__(name_scope)
        self._hidden_size = hidden_size
        self._gate_act = gate_activation or layers.sigmoid
        self._act = activation or layers.tanh
        self._forget_bias = forget_bias
        self._dtype = dtype
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._built = False

    def _build_once(self, input):
        in_dim = int(input.shape[-1])
        H = self._hidden_size
        self._w = self.create_parameter(
            [in_dim + H, 4 * H], attr=self._param_attr, dtype=self._dtype)
        self._b = self.create_parameter(
            [4 * H], attr=self._bias_attr, dtype=self._dtype, is_bias=True)
        self._built = True

    def forward(self, input, pre_hidden, pre_cell):
        if not self._built:
            self._build_once(input)
        concat = layers.concat([input, pre_hidden], axis=1)
        gates = layers.elementwise_add(layers.matmul(concat, self._w),
                                       self._b)
        i, j, f, o = layers.split(gates, 4, dim=1)
        f = layers.scale(f, bias=self._forget_bias)
        new_cell = layers.elementwise_add(
            layers.elementwise_mul(pre_cell, self._gate_act(f)),
            layers.elementwise_mul(self._gate_act(i), self._act(j)))
        new_hidden = layers.elementwise_mul(self._act(new_cell),
                                            self._gate_act(o))
        return new_hidden, new_cell


def _run_static_rnn(input, init_states, step_fn, time_major):
    """Unroll step_fn over time with StaticRNN; input [T,B,D] inside."""
    if not time_major:
        input = layers.transpose(input, [1, 0, 2])
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(input)
        mems = [rnn.memory(init=s) for s in init_states]
        outs, new_states = step_fn(x_t, mems)
        for m, ns in zip(mems, new_states):
            rnn.update_memory(m, ns)
        rnn.step_output(outs)
    out = rnn()
    if not time_major:
        out = layers.transpose(out, [1, 0, 2])
    return out


def _gru_stack(x, init_hidden, hidden_size, num_layers, dropout_prob,
               batch_first, param_attr, bias_attr, gate_activation,
               activation, dtype, name):
    batch_dim = 0 if batch_first else 1
    lasts = []
    for layer in range(num_layers):
        unit = BasicGRUUnit(f"{name}_l{layer}", hidden_size, param_attr,
                            bias_attr, gate_activation, activation, dtype)
        if init_hidden is not None:
            h0 = layers.squeeze(
                layers.slice(init_hidden, axes=[0], starts=[layer],
                             ends=[layer + 1]), [0])
        else:
            h0 = layers.fill_constant_batch_size_like(
                x, [-1, hidden_size], dtype, 0.0,
                input_dim_idx=batch_dim)

        def step(x_t, mems, _unit=unit):
            h = _unit(x_t, mems[0])
            return h, [h]

        x = _run_static_rnn(x, [h0], step, time_major=not batch_first)
        if dropout_prob:
            x = layers.dropout(x, dropout_prob)
        time_axis = 1 if batch_first else 0
        last = layers.slice(x, axes=[time_axis], starts=[-1],
                            ends=[2 ** 31 - 1])
        if batch_first:
            last = layers.transpose(last, [1, 0, 2])  # → [1, B, H]
        lasts.append(last)
    return x, layers.concat(lasts, axis=0)  # out, [num_layers, B, H]


def _run_static_rnn_multi(input, init_states, step_fn, time_major):
    """Like _run_static_rnn but step_fn returns (tuple_of_outputs,
    new_states); all output sequences come back (same layout as input)."""
    if not time_major:
        input = layers.transpose(input, [1, 0, 2])
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(input)
        mems = [rnn.memory(init=s) for s in init_states]
        outs, new_states = step_fn(x_t, mems)
        for m, ns in zip(mems, new_states):
            rnn.update_memory(m, ns)
        rnn.output(*outs)
    result = rnn()
    if not isinstance(result, (list, tuple)):
        result = [result]
    if not time_major:
        result = [layers.transpose(r, [1, 0, 2]) for r in result]
    return tuple(result)


def basic_gru(input, init_hidden, hidden_size, num_layers=1,
              sequence_length=None, dropout_prob=0.0, bidirectional=False,
              batch_first=True, param_attr=None, bias_attr=None,
              gate_activation=None, activation=None, dtype="float32",
              name="basic_gru"):
    """Multi-layer GRU over a sequence (reference rnn_impl.py basic_gru).
    init_hidden: [num_layers(*2 if bidirectional), B, H] or None. Returns
    (output, last_hidden). Fixed-length windows only (the TPU batching
    discipline): ragged batches should be packed/padded upstream."""
    if sequence_length is not None:
        raise NotImplementedError(
            "basic_gru: per-sample sequence_length is not supported — pad "
            "or pack to fixed length (see SURVEY §5 long-context notes)")
    fwd_init = bwd_init = init_hidden
    if init_hidden is not None and bidirectional:
        fwd_init = layers.slice(init_hidden, axes=[0], starts=[0],
                                ends=[num_layers])
        bwd_init = layers.slice(init_hidden, axes=[0],
                                starts=[num_layers],
                                ends=[2 * num_layers])
    out, last = _gru_stack(input, fwd_init, hidden_size, num_layers,
                           dropout_prob, batch_first, param_attr,
                           bias_attr, gate_activation, activation, dtype,
                           name)
    if not bidirectional:
        return out, last
    time_axis = 1 if batch_first else 0
    rev_in = layers.reverse(input, axis=time_axis)
    rout, rlast = _gru_stack(rev_in, bwd_init, hidden_size, num_layers,
                             dropout_prob, batch_first, param_attr,
                             bias_attr, gate_activation, activation,
                             dtype, name + "_reverse")
    rout = layers.reverse(rout, axis=time_axis)
    return (layers.concat([out, rout], axis=2),
            layers.concat([last, rlast], axis=0))


def basic_lstm(input, init_hidden, init_cell, hidden_size, num_layers=1,
               sequence_length=None, dropout_prob=0.0,
               bidirectional=False, batch_first=True, param_attr=None,
               bias_attr=None, gate_activation=None, activation=None,
               forget_bias=1.0, dtype="float32", name="basic_lstm"):
    """Multi-layer LSTM over BasicLSTMUnit (reference rnn_impl.py
    basic_lstm — same gate math incl. forget_bias and custom
    activations). Returns (output, last_hidden, last_cell) with state
    shapes [num_layers(*2 if bidirectional), B, H]."""
    if sequence_length is not None:
        raise NotImplementedError(
            "basic_lstm: per-sample sequence_length is not supported — "
            "pad or pack to fixed length")

    def stack(x, ih, ic, tag):
        batch_dim = 0 if batch_first else 1
        lh, lc = [], []
        for layer in range(num_layers):
            unit = BasicLSTMUnit(f"{tag}_l{layer}", hidden_size,
                                 param_attr, bias_attr, gate_activation,
                                 activation, forget_bias, dtype)

            def pick(src):
                if src is None:
                    return layers.fill_constant_batch_size_like(
                        x, [-1, hidden_size], dtype, 0.0,
                        input_dim_idx=batch_dim)
                return layers.squeeze(
                    layers.slice(src, axes=[0], starts=[layer],
                                 ends=[layer + 1]), [0])

            def step(x_t, mems, _unit=unit):
                h, c = _unit(x_t, mems[0], mems[1])
                return (h, c), [h, c]

            h_seq, c_seq = _run_static_rnn_multi(
                x, [pick(ih), pick(ic)], step,
                time_major=not batch_first)
            if dropout_prob:
                h_seq = layers.dropout(h_seq, dropout_prob)
            x = h_seq
            time_axis = 1 if batch_first else 0
            for seq, acc in ((h_seq, lh), (c_seq, lc)):
                last = layers.slice(seq, axes=[time_axis], starts=[-1],
                                    ends=[2 ** 31 - 1])
                if batch_first:
                    last = layers.transpose(last, [1, 0, 2])
                acc.append(last)
        return x, layers.concat(lh, axis=0), layers.concat(lc, axis=0)

    fwd_ih = bwd_ih = init_hidden
    fwd_ic = bwd_ic = init_cell
    if bidirectional and init_hidden is not None:
        fwd_ih = layers.slice(init_hidden, axes=[0], starts=[0],
                              ends=[num_layers])
        bwd_ih = layers.slice(init_hidden, axes=[0], starts=[num_layers],
                              ends=[2 * num_layers])
    if bidirectional and init_cell is not None:
        fwd_ic = layers.slice(init_cell, axes=[0], starts=[0],
                              ends=[num_layers])
        bwd_ic = layers.slice(init_cell, axes=[0], starts=[num_layers],
                              ends=[2 * num_layers])
    out, lh, lc = stack(input, fwd_ih, fwd_ic, name)
    if not bidirectional:
        return out, lh, lc
    time_axis = 1 if batch_first else 0
    rev = layers.reverse(input, axis=time_axis)
    rout, rlh, rlc = stack(rev, bwd_ih, bwd_ic, name + "_reverse")
    rout = layers.reverse(rout, axis=time_axis)
    return (layers.concat([out, rout], axis=2),
            layers.concat([lh, rlh], axis=0),
            layers.concat([lc, rlc], axis=0))
