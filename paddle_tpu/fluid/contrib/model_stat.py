"""Model parameter/FLOPs summary (reference: contrib/model_stat.py
summary() — walks the program and tabulates per-layer params and FLOPs)."""
from __future__ import annotations

import numpy as np

__all__ = ["summary"]

_CONV_OPS = {"conv2d", "depthwise_conv2d", "conv2d_transpose"}


def _flops_of(op, block):
    try:
        if op.type in _CONV_OPS:
            out = block._find_var_recursive(op.output("Output")[0])
            flt = block._find_var_recursive(op.input("Filter")[0])
            if out is None or flt is None:
                return 0
            o = [d for d in out.shape if d > 0]
            f = list(flt.shape)
            return 2 * int(np.prod(o)) * int(np.prod(f[1:]))
        if op.type in ("mul", "matmul", "matmul_v2"):
            x = block._find_var_recursive(op.input("X")[0])
            y = block._find_var_recursive(op.input("Y")[0])
            if x is None or y is None:
                return 0
            xs = [d for d in x.shape if d > 0]
            ty = op.attrs.get("transpose_Y") or op.attrs.get("trans_y")
            n = int(y.shape[-2]) if ty and len(y.shape) >= 2 \
                else int(y.shape[-1])
            return 2 * int(np.prod(xs)) * n
    except (IndexError, KeyError, ValueError):
        return 0
    return 0


def summary(main_program, print_table: bool = True):
    """Return (total_params, total_flops); optionally print the per-op
    table (reference summary prints the same columns)."""
    total_params = 0
    total_flops = 0
    rows = []
    for block in main_program.blocks:
        for var in block.vars.values():
            from ..framework import Parameter
            # only real Parameters: optimizer accumulators are persistable
            # too and would inflate the count after minimize()
            if isinstance(var, Parameter):
                n = int(np.prod([d for d in var.shape if d > 0] or [0]))
                total_params += n
        for op in block.ops:
            fl = _flops_of(op, block)
            if fl:
                rows.append((op.type, fl))
                total_flops += fl
    if print_table:
        print(f"{'op':<24}{'FLOPs':>16}")
        for t, fl in rows:
            print(f"{t:<24}{fl:>16,}")
        print(f"Total params: {total_params:,}")
        print(f"Total FLOPs:  {total_flops:,} "
              f"({total_flops / 1e9:.3f} GFLOPs)")
    return total_params, total_flops
