"""Legacy decoder API (reference: contrib/decoder/beam_search_decoder.py —
InitState:43, StateCell:159 with @state_updater, TrainingDecoder:384 over
StaticRNN, BeamSearchDecoder:~560 over a while loop with beam_search ops).

TPU mapping: TrainingDecoder rides the framework's StaticRNN (whole
sequence unrolled into one lax.scan inside the jitted step);
BeamSearchDecoder drives the beam_search/beam_search_decode ops through a
host-stepped loop program (each step one compiled computation).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ... import layers
from ...framework import Variable

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]


class InitState:
    """Initial decoder state spec (reference :43): either a boot Variable
    (e.g. encoder final state) or (shape, value) zeros-like spec."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is not None:
            self._init = layers.fill_constant_batch_size_like(
                init_boot, shape=shape, value=value, dtype=dtype)
        else:
            raise ValueError("init or init_boot must be provided")
        self._shape = shape
        self._need_reorder = need_reorder

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell:
    """Named-state RNN cell (reference :159). ``inputs`` maps input names
    to (possibly deferred) variables, ``states`` maps state names to
    InitState. The user decorates an updater::

        @cell.state_updater
        def updater(cell):
            h = cell.get_state('h'); x = cell.get_input('x')
            cell.set_state('h', some_layers(x, h))
    """

    def __init__(self, inputs: Dict[str, Optional[Variable]],
                 states: Dict[str, InitState], out_state: str, name=None):
        self._inputs = dict(inputs)
        self._init_states = dict(states)
        self._out_state = out_state
        self._cur_states: Dict[str, Variable] = {}
        self._updater: Optional[Callable] = None

    # -------------------------------------------------------------- wiring
    def state_updater(self, updater: Callable):
        self._updater = updater
        return updater

    def get_input(self, input_name: str) -> Variable:
        if input_name not in self._inputs or \
                self._inputs[input_name] is None:
            raise ValueError(f"input '{input_name}' not set")
        return self._inputs[input_name]

    def get_state(self, state_name: str) -> Variable:
        if state_name not in self._cur_states:
            raise ValueError(f"state '{state_name}' not initialized")
        return self._cur_states[state_name]

    def set_state(self, state_name: str, state_value: Variable):
        self._cur_states[state_name] = state_value

    def compute_state(self, inputs: Dict[str, Variable]):
        """Bind step inputs and run the updater (reference :335)."""
        for k, v in inputs.items():
            self._inputs[k] = v
        if self._updater is None:
            raise RuntimeError("no @state_updater registered")
        self._updater(self)

    def out_state(self) -> Variable:
        return self._cur_states[self._out_state]


class TrainingDecoder:
    """Teacher-forced decoding over StaticRNN (reference :384)::

        decoder = TrainingDecoder(cell)
        with decoder.block():
            x = decoder.step_input(trg_emb)
            cell.compute_state({'x': x})
            decoder.output(cell.out_state())
        outputs = decoder()
    """

    def __init__(self, state_cell: StateCell, name=None):
        self._state_cell = state_cell
        self._rnn = layers.StaticRNN()
        self._outputs: List[Variable] = []
        self._mems: Dict[str, Variable] = {}

    class _Guard:
        def __init__(self, d):
            self.d = d

        def __enter__(self):
            self.d._ctx = self.d._rnn.step()
            self.d._ctx.__enter__()
            # materialize states as StaticRNN memories
            for name, init in self.d._state_cell._init_states.items():
                mem = self.d._rnn.memory(init=init.value)
                self.d._mems[name] = mem
                self.d._state_cell._cur_states[name] = mem
            return self.d

        def __exit__(self, et, ev, tb):
            if et is not None:
                return False
            # wire state updates back into the rnn memories
            for name, mem in self.d._mems.items():
                new = self.d._state_cell._cur_states[name]
                if new is not mem:
                    self.d._rnn.update_memory(mem, new)
            return self.d._ctx.__exit__(et, ev, tb)

    def block(self):
        return TrainingDecoder._Guard(self)

    def step_input(self, x):
        return self._rnn.step_input(x)

    def static_input(self, x):
        return self._rnn.static_input(x) if hasattr(
            self._rnn, "static_input") else x

    def output(self, *outputs):
        self._rnn.output(*outputs)
        self._outputs = list(outputs)

    def __call__(self):
        return self._rnn()


class BeamSearchDecoder:
    """Beam decoding (reference :560): repeatedly expand candidates with
    the state cell, prune with the beam_search op, stop at end tokens, and
    backtrack with beam_search_decode.

    The decode loop runs on the host; every step's compute is a compiled
    program (static shapes per step), the TPU-friendly equivalent of the
    reference's while-op loop."""

    def __init__(self, state_cell: StateCell, init_ids, init_scores,
                 target_dict_dim, word_dim, input_var_dict=None,
                 topk_size=50, sparse_emb=True, max_len=100, beam_size=4,
                 end_id=1, name=None):
        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._beam_size = beam_size
        self._end_id = end_id
        self._max_len = max_len
        self._word_dim = word_dim
        self._input_var_dict = input_var_dict or {}
        self._embedding_fn: Optional[Callable] = None
        self._scoring_fn: Optional[Callable] = None

    def embedding(self, fn: Callable):
        """Decorator: ids -> word embedding [B, word_dim]."""
        self._embedding_fn = fn
        return fn

    def scoring(self, fn: Callable):
        """Decorator: out_state -> vocab log-probs [B, V]."""
        self._scoring_fn = fn
        return fn

    def decode(self):
        """Build ONE decode step as graph ops: embeds pre_ids, advances the
        state cell, scores, prunes with beam_search. Returns
        (selected_ids, selected_scores, parent_idx) variables; drive it
        from the host loop and finish with beam_search_decode."""
        if self._embedding_fn is None or self._scoring_fn is None:
            raise RuntimeError(
                "register @decoder.embedding and @decoder.scoring first")
        # boot the named states from their InitState specs — overwriting
        # anything a previous TrainingDecoder left behind (its StaticRNN
        # memory placeholders are meaningless outside the training unroll;
        # the reference switches state holders per decoder the same way)
        for name, init in self._state_cell._init_states.items():
            self._state_cell._cur_states[name] = init.value
        pre_ids = self._init_ids
        pre_scores = self._init_scores
        x = self._embedding_fn(pre_ids)
        self._state_cell.compute_state(dict(self._input_var_dict, x=x))
        logits = self._scoring_fn(self._state_cell.out_state())
        probs = layers.softmax(logits)
        topk_scores, topk_ids = layers.topk(probs, k=self._beam_size)
        acc = layers.elementwise_add(
            layers.log(topk_scores),
            layers.reshape(pre_scores, [-1, 1]))
        sel_ids, sel_scores, parent = layers.beam_search(
            pre_ids, pre_scores, topk_ids, acc,
            beam_size=self._beam_size, end_id=self._end_id,
            return_parent_idx=True)
        return sel_ids, sel_scores, parent

    def __call__(self, step_ids_array, step_scores_array):
        """Backtrack full beams (reference beam_search_decode)."""
        return layers.beam_search_decode(step_ids_array, step_scores_array,
                                         beam_size=self._beam_size,
                                         end_id=self._end_id)
