"""Shard a batch reader across trainers (reference:
contrib/reader/distributed_reader.py — round-robin batches by
PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM so each worker sees a disjoint
stream)."""
from __future__ import annotations

import os

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    trainers = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    if trainer_id >= trainers:
        raise ValueError(
            f"PADDLE_TRAINER_ID {trainer_id} >= PADDLE_TRAINERS_NUM "
            f"{trainers}")

    def decorated():
        for i, batch in enumerate(batch_reader()):
            if i % trainers == trainer_id:
                yield batch
    return decorated
