"""extend_optimizer (reference: contrib/extend_optimizer/)."""
from .extend_optimizer_with_weight_decay import (
    extend_with_decoupled_weight_decay, DecoupledWeightDecay)

__all__ = ["extend_with_decoupled_weight_decay", "DecoupledWeightDecay"]
