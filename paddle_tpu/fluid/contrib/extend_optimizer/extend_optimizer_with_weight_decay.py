"""Decoupled weight decay for any optimizer (reference:
contrib/extend_optimizer/extend_optimizer_with_weight_decay.py —
extend_with_decoupled_weight_decay builds an Optimizer subclass that
subtracts lr*coeff*param AFTER the gradient step, i.e. AdamW-style decay
that does not flow through the adaptive moments)."""
from __future__ import annotations

from typing import Callable, Optional, Type

__all__ = ["DecoupledWeightDecay", "extend_with_decoupled_weight_decay"]


class DecoupledWeightDecay:
    """Mixin applied in front of an Optimizer class by
    extend_with_decoupled_weight_decay."""

    def __init__(self, weight_decay: float = 0.0,
                 apply_decay_param_fun: Optional[Callable[[str], bool]]
                 = None, **kwargs):
        self._coeff = float(weight_decay)
        self._apply_decay_param_fun = apply_decay_param_fun
        super().__init__(**kwargs)

    def _decays(self, params_grads):
        for p, g in params_grads:
            if g is None or self._coeff == 0.0:
                continue
            if self._apply_decay_param_fun is not None \
                    and not self._apply_decay_param_fun(p.name):
                continue
            yield p

    def _create_optimization_pass(self, params_grads):
        # hook the path BOTH modes share (dygraph minimize bypasses
        # apply_gradients — dygraph/base.py _dygraph_minimize)
        result = super()._create_optimization_pass(params_grads)
        from ... import framework
        if framework.in_dygraph_mode():
            # eager: scale the updated params in place
            lr = self._get_lr_value()
            for p in self._decays(params_grads):
                p._array = p._array * (1.0 - lr * self._coeff)
            return result
        # static: append param = param*(1 - lr*coeff) after the update ops
        block = framework.default_main_program().global_block()
        for p in self._decays(params_grads):
            lr_var = self._create_param_lr((p, None))
            scaled = block.create_var(
                name=p.name + "@WD", dtype=p.dtype, shape=tuple(p.shape))
            block.append_op(type="elementwise_mul",
                            inputs={"X": [p.name], "Y": [lr_var]},
                            outputs={"Out": [scaled]},
                            attrs={"axis": -1, "_wd_coeff": 1.0})
            coeffed = block.create_var(
                name=p.name + "@WDC", dtype=p.dtype, shape=tuple(p.shape))
            block.append_op(type="scale", inputs={"X": [scaled]},
                            outputs={"Out": [coeffed]},
                            attrs={"scale": self._coeff, "bias": 0.0,
                                   "bias_after_scale": True})
            block.append_op(type="elementwise_sub",
                            inputs={"X": [p.name], "Y": [coeffed]},
                            outputs={"Out": [p.name]},
                            attrs={"axis": -1})
        return result

    def _get_lr_value(self) -> float:
        lr = getattr(self, "_learning_rate", 0.0)
        return float(lr() if callable(lr) else lr)


def extend_with_decoupled_weight_decay(base_optimizer: Type) -> Type:
    """reference extend_with_decoupled_weight_decay(OptimizerClass) →
    OptimizerWithDecoupledWeightDecay."""
    from ...optimizer import Optimizer
    if not (isinstance(base_optimizer, type)
            and issubclass(base_optimizer, Optimizer)):
        raise TypeError("base_optimizer must be an Optimizer subclass")

    class OptimizerWithDecoupledWeightDecay(DecoupledWeightDecay,
                                            base_optimizer):
        def __init__(self, weight_decay, apply_decay_param_fun=None,
                     **kwargs):
            super().__init__(weight_decay=weight_decay,
                             apply_decay_param_fun=apply_decay_param_fun,
                             **kwargs)

    OptimizerWithDecoupledWeightDecay.__name__ = (
        base_optimizer.__name__ + "WithDecoupledWeightDecay")
    return OptimizerWithDecoupledWeightDecay
