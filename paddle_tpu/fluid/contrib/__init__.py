"""fluid.contrib (reference: python/paddle/fluid/contrib/): mixed
precision, slim compression toolkit, decoupled-weight-decay optimizers,
memory/FLOPs estimators, op frequency stats."""
from . import mixed_precision  # noqa: F401
from . import slim  # noqa: F401
from . import extend_optimizer  # noqa: F401
from . import decoder  # noqa: F401
from .extend_optimizer import (  # noqa: F401
    extend_with_decoupled_weight_decay, DecoupledWeightDecay)
from .memory_usage_calc import memory_usage  # noqa: F401
from .op_frequence import op_freq_statistic  # noqa: F401
from .model_stat import summary  # noqa: F401
from . import layers  # noqa: F401
from . import reader  # noqa: F401
from . import quantize  # noqa: F401
from . import utils  # noqa: F401
