"""fluid.contrib (reference: python/paddle/fluid/contrib/) — mixed precision
lands here; slim/quant in a later round."""
from . import mixed_precision  # noqa: F401
from . import slim  # noqa: F401
