"""Estimate a program's activation+param memory (reference:
contrib/memory_usage_calc.py memory_usage:46 — sums var bytes with the
batch dim substituted). On TPU this is the HBM footprint estimate before
XLA's buffer sharing; useful for picking batch size / remat points."""
from __future__ import annotations

import numpy as np

from ..core import dtype_to_np

__all__ = ["memory_usage"]

_GB = 1024 ** 3


def memory_usage(program, batch_size: int):
    """Return (lower_gb, upper_gb) like the reference (the upper bound
    adds a 1.5x slack for fusion temporaries)."""
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    total = 0
    for block in program.blocks:
        for var in block.vars.values():
            shape = [batch_size if d in (-1, 0) else d for d in var.shape]
            if not shape:
                shape = [1]
            try:
                itemsize = np.dtype(dtype_to_np(var.dtype)).itemsize
            except Exception:
                itemsize = 4
            total += int(np.prod(shape)) * itemsize
    return total / _GB, total * 1.5 / _GB
