"""QuantizeTranspiler (reference: contrib/quantize/quantize_transpiler.py
— training_transpile inserts fake-quant ops; freeze_program flips them for
deployment). Delegates to the slim quantization pass, which owns the
program rewrite in this build."""
from __future__ import annotations

from ..slim.quantization.quantization_pass import (quantize_program,
                                                   QuantizationFreezePass)

__all__ = ["QuantizeTranspiler"]


class QuantizeTranspiler:
    _ACT_TYPES = ("abs_max", "moving_average_abs_max")
    _WEIGHT_TYPES = ("abs_max",)

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 activation_quantize_type: str = "abs_max",
                 weight_quantize_type: str = "abs_max",
                 window_size: int = 10000, moving_rate: float = 0.9):
        if activation_quantize_type not in self._ACT_TYPES:
            raise NotImplementedError(
                f"activation_quantize_type "
                f"'{activation_quantize_type}' not supported; one of "
                f"{self._ACT_TYPES}")
        if weight_quantize_type not in self._WEIGHT_TYPES:
            raise NotImplementedError(
                f"weight_quantize_type '{weight_quantize_type}' not "
                f"supported; one of {self._WEIGHT_TYPES}")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate

    def training_transpile(self, program=None, startup_program=None):
        """Insert fake quant-dequant for QAT (reference
        training_transpile)."""
        return quantize_program(program, startup_program,
                                weight_bits=self.weight_bits,
                                activation_bits=self.activation_bits,
                                moving_rate=self.moving_rate)

    def freeze_program(self, program, place=None, scope=None):
        """Flip quant ops to inference mode (reference freeze_program)."""
        return QuantizationFreezePass().apply(program)
