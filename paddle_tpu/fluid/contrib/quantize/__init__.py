"""contrib.quantize (reference: contrib/quantize/quantize_transpiler.py —
the pre-slim quantization transpiler; same program rewrite as
slim.quantization here)."""
from .quantize_transpiler import QuantizeTranspiler

__all__ = ["QuantizeTranspiler"]
