"""AMP (reference: python/paddle/fluid/contrib/mixed_precision/ —
decorator.py:218 decorate → OptimizerWithMixedPrecision:27, white/black op
lists fp16_lists.py, cast insertion fp16_utils.py, dynamic loss scaling).

TPU inversion: the numerically-safe reduced precision is bfloat16, which
needs NO loss scaling (same exponent range as fp32). ``decorate`` keeps the
reference API: it rewrites matmul/conv inputs to bf16 (white list) while
keeping softmax/norm accumulation fp32 (black list), and exposes the loss
scaling knobs as inert attributes for script parity."""
from .decorator import decorate, AutoMixedPrecisionLists  # noqa: F401
