"""AMP decorator (reference: contrib/mixed_precision/decorator.py:218).

bf16-first design: white-list ops (matmul/mul/conv2d — the MXU ops) get
their float inputs cast to bf16; black-list ops stay fp32. Parameters remain
fp32 master copies; casts are inserted as graph ops so the whole thing still
jits into one XLA computation where the casts fuse away.

Loss scaling: bf16 needs none (exponent range equals fp32), so by default
the scale API is preserved but inert. ``use_fp16=True`` (or any narrow
format whose exponent underflows) turns on REAL dynamic loss scaling
(reference decorator.py scaled_loss + update_loss_scaling): the loss is
multiplied by a persistable ``loss_scaling`` var before backward, the
grads divide it back out before the update, and the scale/counter
transition is fused into the executor's step epilogue — it consumes the
SAME health scalar the FLAGS_check_nan_inf numeric fault guard computes
(executor._amp_scale_update; docs/FAULT_TOLERANCE.md "Numeric faults")
instead of re-reducing the grads, and an overflowed step is discarded
whole by the guard's fused select (params and optimizer slots revert,
the scale still updates). The state rides ``program._amp_dynamic``."""
from __future__ import annotations

from typing import Optional, Set

from ... import unique_name
from ...core import VarDesc
from ...framework import (default_main_program, default_startup_program,
                          Variable)

__all__ = ["decorate", "AutoMixedPrecisionLists"]

WHITE_LIST = {"matmul", "matmul_v2", "mul", "conv2d", "depthwise_conv2d",
              "conv3d", "bmm"}
BLACK_LIST = {"softmax", "softmax_with_cross_entropy", "cross_entropy",
              "cross_entropy2", "exp", "log", "mean", "sum", "layer_norm",
              "batch_norm", "reduce_mean", "reduce_sum"}


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(WHITE_LIST)
        self.black_list = set(BLACK_LIST)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
        self.black_varnames = set(custom_black_varnames or [])


def _insert_casts(program, amp_lists: AutoMixedPrecisionLists):
    """Rewrite the main block: inputs of white-list ops cast to bf16, their
    outputs cast back to fp32 (XLA folds redundant pairs)."""
    block = program.global_block()
    new_ops = []
    cast_cache = {}
    idx = 0
    for op in list(block.ops):
        if op.type in amp_lists.white_list:
            for slot, names in op.inputs.items():
                for k, n in enumerate(names):
                    v = block.vars.get(n)
                    if v is None or v.dtype != VarDesc.VarType.FP32:
                        continue
                    if n in amp_lists.black_varnames:
                        continue
                    key = n
                    if key not in cast_cache:
                        cast_name = n + ".cast_bf16"
                        block.create_var(name=cast_name,
                                         dtype=VarDesc.VarType.BF16,
                                         shape=v.shape, persistable=False)
                        cast_cache[key] = cast_name
                        new_ops.append((op, {"type": "cast",
                                             "inputs": {"X": [n]},
                                             "outputs": {"Out": [cast_name]},
                                             "attrs": {"in_dtype": v.dtype,
                                                       "out_dtype":
                                                       VarDesc.VarType.BF16}}))
                    names[k] = cast_cache[key]
            for slot, names in op.outputs.items():
                for n in names:
                    v = block.vars.get(n)
                    if v is not None:
                        v.dtype = VarDesc.VarType.BF16
    # splice cast ops before their consumers
    for anchor, desc in new_ops:
        pos = block.ops.index(anchor)
        block._insert_op(pos, type=desc["type"], inputs=desc["inputs"],
                         outputs=desc["outputs"], attrs=desc["attrs"])
    return program


def _create_persistable(main_block, startup_block, name, dtype, value):
    """One [1]-shaped persistable state var declared in BOTH programs and
    filled by the startup program (the pattern of the reference's
    create_global_var + loss-scaling initializers)."""
    v = main_block.create_var(name=name, dtype=dtype, shape=(1,),
                              persistable=True)
    v.stop_gradient = True
    startup_block.create_var(name=name, dtype=dtype, shape=(1,),
                             persistable=True)
    startup_block.append_op(type="fill_constant", inputs={},
                            outputs={"Out": [name]},
                            attrs={"shape": [1], "dtype": dtype,
                                   "value": float(value)})
    return v


class OptimizerWithMixedPrecision:
    """reference decorator.py:27."""

    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
                 use_fp16=False):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = init_loss_scaling
        self._loss_scaling = init_loss_scaling
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling
        self._incr_every_n_steps = int(incr_every_n_steps)
        self._decr_every_n_nan_or_inf = int(decr_every_n_nan_or_inf)
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._use_fp16 = bool(use_fp16)
        self._scale_var: Optional[Variable] = None
        self._train_program = None

    @property
    def _scaling_enabled(self) -> bool:
        # bf16 exponent range equals fp32 — scaling only matters when the
        # user forces the narrow-mantissa fp16-style contract; with it,
        # use_dynamic_loss_scaling picks dynamic vs STATIC scaling (the
        # reference scales whenever fp16 is on — a requested
        # init_loss_scaling must never be silently dropped)
        return self._use_fp16

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        program = loss.block.program
        _insert_casts(program, self._amp_lists)
        if not self._scaling_enabled:
            return self._optimizer.backward(
                loss, startup_program, parameter_list, no_grad_set,
                callbacks)
        if not self._use_dynamic_loss_scaling:
            # STATIC scaling: loss * constant before backward, grads /
            # constant in apply_gradients — no state vars, no executor
            # epilogue involvement
            main_block = loss.block
            scaled = main_block.create_var(
                name=unique_name.generate(loss.name + ".scaled"),
                dtype=loss.dtype, persistable=False)
            scaled.shape = loss.shape
            scaled.stop_gradient = False
            main_block.append_op(
                type="scale", inputs={"X": [loss.name]},
                outputs={"Out": [scaled.name]},
                attrs={"scale": float(self._init_loss_scaling),
                       "bias": 0.0, "bias_after_scale": True})
            return self._optimizer.backward(
                scaled, startup_program, parameter_list, no_grad_set,
                callbacks)
        # dynamic loss scaling: backward runs on loss * loss_scaling so
        # small grads survive the narrow format; the executor's fused
        # guard epilogue owns the scale/counter transition (and the
        # overflow-step discard), keyed off program._amp_dynamic
        startup = startup_program or default_startup_program()
        main_block = loss.block
        startup_block = startup.global_block()
        scale = _create_persistable(
            main_block, startup_block, unique_name.generate("loss_scaling"),
            VarDesc.VarType.FP32, self._init_loss_scaling)
        good = _create_persistable(
            main_block, startup_block,
            unique_name.generate("loss_scaling_good"),
            VarDesc.VarType.INT32, 0)
        bad = _create_persistable(
            main_block, startup_block,
            unique_name.generate("loss_scaling_bad"),
            VarDesc.VarType.INT32, 0)
        self._scale_var = scale
        scaled = main_block.create_var(
            name=unique_name.generate(loss.name + ".scaled"),
            dtype=loss.dtype, persistable=False)
        scaled.shape = loss.shape
        scaled.stop_gradient = False
        main_block.append_op(type="elementwise_mul",
                             inputs={"X": [loss.name], "Y": [scale.name]},
                             outputs={"Out": [scaled.name]},
                             attrs={"axis": -1})
        program._amp_dynamic = {
            "scale": scale.name, "good": good.name, "bad": bad.name,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
        }
        return self._optimizer.backward(
            scaled, startup_program, parameter_list, no_grad_set,
            callbacks)

    def apply_gradients(self, params_grads):
        # cast bf16 grads up to fp32 before the update (master weights),
        # then divide the loss scale back out (reference
        # check_finite_and_unscale's unscale half; the finite half is the
        # executor's fused health scalar)
        from ...layers import tensor as _t
        fixed = []
        for p, g in params_grads:
            if g is not None and g.dtype == VarDesc.VarType.BF16:
                g = _t.cast(g, VarDesc.VarType.FP32)
            if g is not None and self._scaling_enabled:
                block = g.block
                un = block.create_var(
                    name=unique_name.generate(g.name + ".unscaled"),
                    dtype=g.dtype, persistable=False)
                un.shape = g.shape
                if self._use_dynamic_loss_scaling:
                    block.append_op(
                        type="elementwise_div",
                        inputs={"X": [g.name],
                                "Y": [self._scale_var.name]},
                        outputs={"Out": [un.name]}, attrs={"axis": -1})
                else:  # static: divide by the compile-time constant
                    block.append_op(
                        type="scale", inputs={"X": [g.name]},
                        outputs={"Out": [un.name]},
                        attrs={"scale":
                               1.0 / float(self._init_loss_scaling),
                               "bias": 0.0, "bias_after_scale": True})
                g = un
            fixed.append((p, g))
        return self._optimizer.apply_gradients(fixed)

    def apply_optimize(self, loss, startup_program, params_grads):
        # MUST route through the wrapper's apply_gradients: the inner
        # optimizer's apply_optimize would apply the still-SCALED (and
        # possibly bf16) grads raw — a 2**15x update on the split
        # backward()/apply_optimize() API path. Same program_guard as
        # the base Optimizer.apply_optimize, so accumulator/LR init ops
        # land in the CALLER'S startup program.
        from ...framework import default_startup_program, program_guard
        program = loss.block.program
        with program_guard(program,
                           startup_program or default_startup_program()):
            return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    @property
    def _loss_scaling_var(self):
        return self._scale_var


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, use_fp16=False):
    """reference decorator.py:218. ``use_fp16=True`` activates real
    dynamic loss scaling (see the module docstring); the bf16 default
    keeps the pre-existing inert-scale behavior."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        use_fp16=use_fp16)
