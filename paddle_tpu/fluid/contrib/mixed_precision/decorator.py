"""AMP decorator (reference: contrib/mixed_precision/decorator.py:218).

bf16-first design: white-list ops (matmul/mul/conv2d — the MXU ops) get
their float inputs cast to bf16; black-list ops stay fp32. Parameters remain
fp32 master copies; casts are inserted as graph ops so the whole thing still
jits into one XLA computation where the casts fuse away. No loss scaling is
required for bf16 (exponent range equals fp32); the scale API is preserved
and applied only when use_fp16=True is forced."""
from __future__ import annotations

from typing import Optional, Set

from ...core import VarDesc
from ...framework import default_main_program, Variable

__all__ = ["decorate", "AutoMixedPrecisionLists"]

WHITE_LIST = {"matmul", "matmul_v2", "mul", "conv2d", "depthwise_conv2d",
              "conv3d", "bmm"}
BLACK_LIST = {"softmax", "softmax_with_cross_entropy", "cross_entropy",
              "cross_entropy2", "exp", "log", "mean", "sum", "layer_norm",
              "batch_norm", "reduce_mean", "reduce_sum"}


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(WHITE_LIST)
        self.black_list = set(BLACK_LIST)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
        self.black_varnames = set(custom_black_varnames or [])


def _insert_casts(program, amp_lists: AutoMixedPrecisionLists):
    """Rewrite the main block: inputs of white-list ops cast to bf16, their
    outputs cast back to fp32 (XLA folds redundant pairs)."""
    block = program.global_block()
    new_ops = []
    cast_cache = {}
    idx = 0
    for op in list(block.ops):
        if op.type in amp_lists.white_list:
            for slot, names in op.inputs.items():
                for k, n in enumerate(names):
                    v = block.vars.get(n)
                    if v is None or v.dtype != VarDesc.VarType.FP32:
                        continue
                    if n in amp_lists.black_varnames:
                        continue
                    key = n
                    if key not in cast_cache:
                        cast_name = n + ".cast_bf16"
                        block.create_var(name=cast_name,
                                         dtype=VarDesc.VarType.BF16,
                                         shape=v.shape, persistable=False)
                        cast_cache[key] = cast_name
                        new_ops.append((op, {"type": "cast",
                                             "inputs": {"X": [n]},
                                             "outputs": {"Out": [cast_name]},
                                             "attrs": {"in_dtype": v.dtype,
                                                       "out_dtype":
                                                       VarDesc.VarType.BF16}}))
                    names[k] = cast_cache[key]
            for slot, names in op.outputs.items():
                for n in names:
                    v = block.vars.get(n)
                    if v is not None:
                        v.dtype = VarDesc.VarType.BF16
    # splice cast ops before their consumers
    for anchor, desc in new_ops:
        pos = block.ops.index(anchor)
        block._insert_op(pos, type=desc["type"], inputs=desc["inputs"],
                         outputs=desc["outputs"], attrs=desc["attrs"])
    return program


class OptimizerWithMixedPrecision:
    """reference decorator.py:27."""

    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scaling = init_loss_scaling
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling
        self._train_program = None

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        # bf16: no scaled loss needed; run standard backward on the
        # cast-rewritten program
        program = loss.block.program
        _insert_casts(program, self._amp_lists)
        params_grads = self._optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set, callbacks)
        return params_grads

    def apply_gradients(self, params_grads):
        # cast bf16 grads up to fp32 before the update (master weights)
        from ...layers import tensor as _t
        fixed = []
        for p, g in params_grads:
            if g is not None and g.dtype == VarDesc.VarType.BF16:
                fixed.append((p, _t.cast(g, VarDesc.VarType.FP32)))
            else:
                fixed.append((p, g))
        return self._optimizer.apply_gradients(fixed)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self._optimizer.apply_optimize(loss, startup_program,
                                              params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    @property
    def _loss_scaling_var(self):
        return None


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True):
    """reference decorator.py:218."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio)
